"""graftlint tier-1 gate: every rule fires on its seeded fixture, every
clean fixture passes, and the repo itself is clean against the checked-in
baseline.

Three layers:

1. **Fixture corpus** (``tests/fixtures/lint/``) — seeded violations per
   rule id; proves each rule detects its failure class and that the
   guarded twins don't trip it (false-positive control).
2. **Baseline machinery** — the TOML-subset parser, suppression matching
   on snippets (line-churn-proof), and unused-entry reporting.
3. **Repo gate** — passes 1+3 run in-process over the repo (pure AST,
   fast); pass 2 runs via the ``tools/graftlint.py`` subprocess because
   the AOT path mutates process env (forced compiled Pallas kernels) —
   importing it here would poison this pytest process. Off-TPU toolchains
   skip the AOT half gracefully (the driver reports it, we accept it).
"""

import json
import os
import subprocess
import sys

import pytest

from tpu_sandbox.analysis import (
    BaselineError,
    apply_baseline,
    parse_baseline,
    render_baseline,
    run_collective_pass,
    run_control_pass,
)
from tpu_sandbox.analysis.collective_pass import lint_source as lint_coll
from tpu_sandbox.analysis.control_pass import lint_source as lint_ctrl
from tpu_sandbox.analysis.findings import RULES, make_finding
from tpu_sandbox.analysis.hlo_pass import (
    lint_donation,
    lint_hlo_text,
    lint_int8_padding,
    lint_jaxpr,
    lint_schedule,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "lint")
BASELINE = os.path.join(ROOT, "tpu_sandbox", "analysis", "baseline.toml")


def _fixture(name: str) -> str:
    with open(os.path.join(FIXTURES, name), "r", encoding="utf-8") as f:
        return f.read()


# ---------------------------------------------------------------------------
# Pass 1 fixtures
# ---------------------------------------------------------------------------


def test_bad_collective_fixture_fires_every_rule():
    findings = lint_coll(_fixture("bad_collective.py"), "bad_collective.py")
    rules = {f.rule for f in findings}
    assert {"GL-C101", "GL-C102", "GL-C103"} <= rules
    # every seeded function is caught
    msgs = "\n".join(f.message for f in findings)
    assert "pmean" in msgs          # rank_branch_collective
    assert "psum" in msgs           # rank_early_exit
    assert "_helper_syncs" in msgs  # rank_branch_calls_helper (via summary)
    assert "all_gather" in msgs     # rank_cond_lambda
    assert "ppermute" in msgs       # rank_while_collective
    # self-call resolution through the class method table: ShardSyncB's
    # rank-gated self._sync() fires even though _ShardSyncA owns a
    # collective-free method of the same name (the old bare-name table
    # let A answer for B)
    c103 = [f for f in findings if f.rule == "GL-C103"]
    assert len(c103) == 2
    assert any("'_sync'" in f.message for f in c103)
    # the name-shadowed ShardSyncB.gated is linted as its own function
    # (it used to be skipped entirely once A.gated took the bare slot)
    assert sum(1 for f in findings if f.rule == "GL-C101") >= 4
    # findings carry real locations + hints
    assert all(f.line > 0 and f.hint for f in findings)


def test_clean_collective_fixture_passes():
    findings = lint_coll(
        _fixture("clean_collective.py"), "clean_collective.py")
    assert findings == [], [f.format() for f in findings]


# ---------------------------------------------------------------------------
# Cross-module resolution (xmodule.CrossIndex)
# ---------------------------------------------------------------------------


def _xmodule_paths(*names):
    return [os.path.join(FIXTURES, n) for n in names]


def test_cross_module_fixture_fires_through_imports():
    """Collective-bearing calls hidden one (or two) imports away resolve
    when the file set is linted together: from-import, module-attribute,
    post-rank-exit depth-2 chain, and a jit of an imported sync fn."""
    paths = _xmodule_paths("xmodule_helper.py", "bad_xmodule.py")
    findings = run_collective_pass(FIXTURES, paths=paths) \
        + run_control_pass(FIXTURES, paths=paths)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    assert set(by_rule) == {"GL-C102", "GL-C103", "GL-R305"}, \
        [f.format() for f in findings]
    # both import spellings of the rank-gated sync fire
    assert len(by_rule["GL-C103"]) == 2
    assert all("sync_all" in f.message for f in by_rule["GL-C103"])
    # bearing crossed the import edge AND a local hop inside the helper
    assert "sync_step" in by_rule["GL-C102"][0].message
    assert "stepper" in by_rule["GL-R305"][0].snippet
    # the helper module itself carries no findings
    assert all(f.file.endswith("bad_xmodule.py") for f in findings)


def test_cross_module_clean_twin_passes():
    paths = _xmodule_paths("xmodule_helper.py", "clean_xmodule.py")
    findings = run_collective_pass(FIXTURES, paths=paths) \
        + run_control_pass(FIXTURES, paths=paths)
    assert findings == [], [f.format() for f in findings]


def test_cross_module_dotted_receivers_fire():
    """``pkg.mod.fn()`` and ``alias.submodule.fn()`` receivers resolve by
    longest import-alias prefix — the PR-19 remainder. Both rank-gated
    dotted spellings fire, and the depth-2 chain crosses the dotted
    edge after a rank exit."""
    paths = _xmodule_paths(os.path.join("xpkg", "helpers.py"),
                          "bad_xdotted.py")
    findings = run_collective_pass(FIXTURES, paths=paths)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    assert set(by_rule) == {"GL-C102", "GL-C103"}, \
        [f.format() for f in findings]
    assert len(by_rule["GL-C103"]) == 2
    assert all("sync_all" in f.message for f in by_rule["GL-C103"])
    assert "sync_step" in by_rule["GL-C102"][0].message
    assert all(f.file.endswith("bad_xdotted.py") for f in findings)


def test_cross_module_dotted_clean_twin_passes():
    """Same dotted receivers, unconditional (or collective-free): the
    resolution must prove absence as well as presence."""
    paths = _xmodule_paths(os.path.join("xpkg", "helpers.py"),
                          "clean_xdotted.py")
    findings = run_collective_pass(FIXTURES, paths=paths) \
        + run_control_pass(FIXTURES, paths=paths)
    assert findings == [], [f.format() for f in findings]


def test_cross_module_bad_file_reads_clean_alone():
    """Single-file lint cannot see through imports — the asymmetry that
    makes the whole-set run the only honest gate. If this starts firing,
    the fixture's imports got inlined and the cross-module test above
    stopped proving anything."""
    findings = lint_coll(_fixture("bad_xmodule.py"), "bad_xmodule.py")
    assert findings == [], [f.format() for f in findings]


# ---------------------------------------------------------------------------
# Pass 3 fixtures
# ---------------------------------------------------------------------------


def test_bad_control_fixture_fires_every_rule():
    findings = lint_ctrl(_fixture("bad_control.py"), "bad_control.py")
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    assert set(by_rule) == {"GL-R301", "GL-R302", "GL-R303", "GL-R304",
                            "GL-R305", "GL-R306"}
    # both claim spellings: constant key AND unscoped key helper
    assert len(by_rule["GL-R301"]) == 2
    # the unbounded queue anchors on the append site
    assert "waiting" in by_rule["GL-R306"][0].message
    # leader-reachability: the blocking get() is inside _resolve, reached
    # from _leader_tick
    assert "_resolve" in by_rule["GL-R304"][0].message
    # ...and through the inheritance edge: _BaseResolver._lookup is only
    # leader-reachable via BadLeaderSub's _leader_sync
    assert len(by_rule["GL-R304"]) == 2
    assert "BadLeaderSub._lookup" in by_rule["GL-R304"][1].message
    # the launch storm anchors on the dispatch site inside the loop
    assert "_sync_grads" in by_rule["GL-R305"][0].snippet


def test_clean_control_fixture_passes():
    findings = lint_ctrl(_fixture("clean_control.py"), "clean_control.py")
    assert findings == [], [f.format() for f in findings]


def test_bad_obs_fixture_fires_gl_o401():
    findings = lint_ctrl(_fixture("bad_obs.py"), "bad_obs.py")
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    # the obs fixture trips ONLY the span-leak rule — three spellings
    assert set(by_rule) == {"GL-O401"}
    assert len(by_rule["GL-O401"]) == 3
    msgs = "\n".join(f.message for f in by_rule["GL-O401"])
    assert "discarded" in msgs          # handle_discarded
    assert "'sp'" in msgs               # assigned-but-unguarded spellings
    assert all(f.line > 0 and f.hint for f in findings)


def test_clean_obs_fixture_passes():
    findings = lint_ctrl(_fixture("clean_obs.py"), "clean_obs.py")
    assert findings == [], [f.format() for f in findings]


def test_bad_metrics_fixture_fires_gl_o402():
    findings = lint_ctrl(_fixture("bad_metrics.py"), "bad_metrics.py")
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    # trips ONLY the metric-name rule — three spellings: f-string,
    # concatenation, flat (undotted) literal
    assert set(by_rule) == {"GL-O402"}
    assert len(by_rule["GL-O402"]) == 3
    msgs = "\n".join(f.message for f in by_rule["GL-O402"])
    assert "counter()" in msgs
    assert "gauge()" in msgs
    assert "histogram()" in msgs
    assert all(f.line > 0 and f.hint for f in findings)


def test_clean_metrics_fixture_passes():
    findings = lint_ctrl(_fixture("clean_metrics.py"), "clean_metrics.py")
    assert findings == [], [f.format() for f in findings]


def test_bad_spans_fixture_fires_gl_o403():
    findings = lint_ctrl(_fixture("bad_spans.py"), "bad_spans.py")
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    # trips ONLY the span-name rule — three spellings: f-string without a
    # family prefix, %-formatting, bare variable
    assert set(by_rule) == {"GL-O403"}
    assert len(by_rule["GL-O403"]) == 3
    msgs = "\n".join(f.message for f in by_rule["GL-O403"])
    assert "span()" in msgs
    assert "complete()" in msgs
    assert "instant()" in msgs
    assert all(f.line > 0 and f.hint for f in findings)


def test_clean_spans_fixture_passes():
    # static literals, colon families, the sanctioned f"family:{value}"
    # shape, keyword name=, and non-recorder receivers all stay silent
    findings = lint_ctrl(_fixture("clean_spans.py"), "clean_spans.py")
    assert findings == [], [f.format() for f in findings]


# ---------------------------------------------------------------------------
# Pass 2 fixtures (pure layers; the compile layer runs in the subprocess
# gate below)
# ---------------------------------------------------------------------------


def test_donation_rule_h201():
    bad, entry = lint_donation(
        "dp", donate_requested=True, alias_bytes=0, output_bytes=650_000)
    assert [f.rule for f in bad] == ["GL-H201"]
    assert entry["donation"] == "missing"
    clean, entry = lint_donation(
        "dp", donate_requested=True,
        alias_bytes=649_000, output_bytes=650_000)
    assert clean == [] and entry["donation"] == "verified"


def test_upcast_rule_h202_jaxpr():
    import jax
    import jax.numpy as jnp

    def bad(x):
        return x.astype(jnp.float32) * 2.0  # large bf16->f32 upcast

    def clean(x):
        # NOTE: jnp.sum would NOT be clean — it upcasts the bf16
        # accumulator to f32 (the rule caught that in an earlier draft of
        # this very test)
        return x * 2.0  # stays bf16

    big = jnp.zeros((128, 64), jnp.bfloat16)
    fired = lint_jaxpr(jax.make_jaxpr(bad)(big), "fix")
    assert [f.rule for f in fired] == ["GL-H202"]
    assert lint_jaxpr(jax.make_jaxpr(clean)(big), "fix") == []
    # below the element threshold: noise, not a finding
    small = jnp.zeros((8,), jnp.bfloat16)
    assert lint_jaxpr(jax.make_jaxpr(bad)(small), "fix") == []


def test_host_transfer_rule_h203():
    import jax
    import jax.numpy as jnp

    def bad(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    x = jnp.zeros((4,), jnp.float32)
    fired = lint_jaxpr(jax.make_jaxpr(bad)(x), "fix")
    assert "GL-H203" in {f.rule for f in fired}
    assert lint_jaxpr(jax.make_jaxpr(lambda v: v * 2)(x), "fix") == []
    # HLO-text spelling of the same class
    hlo_bad = ('  %send = f32[8] custom-call(f32[8] %p0), '
               'custom_call_target="SendToHost"\n')
    assert [f.rule for f in lint_hlo_text(hlo_bad, "fix")] == ["GL-H203"]
    assert lint_hlo_text("  %a = f32[8] add(f32[8] %p0, f32[8] %p0)\n",
                         "fix") == []


def test_schedule_rule_h204():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from hlo_schedule import schedule_report

    from tests.test_hlo_tools import _MONO_HLO, _OVERLAP_HLO

    mono = schedule_report(_MONO_HLO)
    fired = lint_schedule(mono, "dp-overlap", overlap=True)
    assert [f.rule for f in fired] == ["GL-H204"]
    # same schedule without overlap requested: the monolithic baseline is
    # legitimate, not a finding
    assert lint_schedule(mono, "dp", overlap=False) == []
    assert lint_schedule(
        schedule_report(_OVERLAP_HLO), "dp-overlap", overlap=True) == []


def test_int8_padding_rule_h205():
    # 30 tiny leaves: block/axis alignment zeros dwarf the payload
    fired, wire = lint_int8_padding([16] * 30, 8, label="fix")
    assert [f.rule for f in fired] == ["GL-H205"]
    assert wire["overhead_fraction"] > 0.25
    # one large aligned leaf: scales overhead only, well under threshold
    clean, wire = lint_int8_padding([262_144], 8, label="fix")
    assert clean == [] and wire["overhead_fraction"] < 0.05


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------


def test_baseline_roundtrip_and_unused_reporting():
    f1 = make_finding("GL-R303", "a.py", 10, "thread", snippet="t = Thread()")
    f2 = make_finding("GL-R301", "b.py", 20, "claim", snippet="kv.add(k, 1)")
    text = render_baseline([f1])
    sups = parse_baseline(text)
    assert len(sups) == 1 and sups[0].rule == "GL-R303"
    kept, suppressed, unused = apply_baseline([f1, f2], sups)
    assert kept == [f2] and suppressed == [f1] and unused == []
    # snippet-substring matching survives line churn
    f1_moved = make_finding("GL-R303", "a.py", 99, "thread",
                            snippet="t = Thread()")
    kept, suppressed, _ = apply_baseline([f1_moved], sups)
    assert kept == [] and suppressed == [f1_moved]
    # unused entries are surfaced for deletion
    _, _, unused = apply_baseline([f2], sups)
    assert unused == sups


def test_baseline_parser_rejects_malformed():
    with pytest.raises(BaselineError):
        parse_baseline('rule = "GL-R303"')  # key outside a table
    with pytest.raises(BaselineError):
        parse_baseline('[[suppress]]\nrule = unquoted')
    with pytest.raises(BaselineError):
        parse_baseline('[[suppress]]\nfile = "a.py"')  # missing rule
    assert parse_baseline("# comment only\n") == []


def test_rule_catalog_is_complete():
    prefixes = {r[:5] for r in RULES}
    assert prefixes == {"GL-C1", "GL-H2", "GL-R3", "GL-O4"}
    assert all(title and hint for title, hint in RULES.values())


# ---------------------------------------------------------------------------
# repo gate
# ---------------------------------------------------------------------------


def test_repo_ast_passes_clean_against_baseline():
    """Passes 1+3 over the repo must be clean modulo the checked-in
    baseline — THE ratchet. A new finding means: fix it or triage it into
    analysis/baseline.toml with a reason."""
    from tpu_sandbox.analysis import load_baseline

    findings = run_collective_pass(ROOT) + run_control_pass(ROOT)
    kept, _, unused = apply_baseline(findings, load_baseline(BASELINE))
    assert kept == [], (
        "new graftlint findings (fix or triage into baseline.toml):\n"
        + "\n".join(f.format() for f in kept)
    )
    assert unused == [], (
        "stale baseline entries (delete them):\n"
        + "\n".join(f"{s.rule} {s.file} {s.match!r}" for s in unused)
    )


def _run_graftlint(*extra):
    """graftlint in a subprocess: the AOT tools mutate process env
    (forced compiled Pallas kernels), so pass 2's compile layer must
    never run inside this long-lived pytest process."""
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "graftlint.py"),
         "--all", "--json", *extra],
        capture_output=True, text=True, timeout=600, cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"graftlint exited {proc.returncode}:\n{proc.stdout}\n{proc.stderr}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_graftlint_cli_traces_all_steps():
    """Tier-1 half of the CLI gate: all three passes, jaxpr-tracing the
    real DP/ZeRO/pjit/pipeline steps — plus the engine-flag variants
    (int8 grad compress, bucketed overlap), SeqParallel, and the serve
    decode + bucketed-prefill steps — on CPU. The AOT compiles are skipped here (`--no-aot`)
    to keep tier-1 inside its time budget — the full chipless AOT receipt
    runs in the slow twin below."""
    report = _run_graftlint("--no-aot")
    assert report["findings"] == 0
    assert report["unused_suppressions"] == 0
    hlo = report["hlo"]
    for step in ("dp", "zero", "pjit", "pipeline", "dp-int8",
                 "dp-overlap", "sp", "decode", "prefill", "prefill-b16",
                 "fsdp", "tp", "ep", "mpmd-s0-fwd", "mpmd-s0-bwd",
                 "mpmd-s1-loss_grad"):
        assert hlo[step]["status"] == "traced", hlo


@pytest.mark.slow
def test_graftlint_cli_full_run_including_aot():
    """Pass 2 end-to-end: AOT-compiles the DP/ZeRO steps against the
    chipless v5e topology and verifies donation, overlap scheduling, and
    int8 wire padding. Skips gracefully where the toolchain can't build
    topologies."""
    report = _run_graftlint()
    assert report["findings"] == 0
    aot = report["hlo"]["aot"]
    if aot.get("status") == "skipped":
        pytest.skip(f"AOT toolchain unavailable: {aot.get('reason')}")
    # the acceptance receipt: donation status for the DP and ZeRO steps
    assert aot["dp"]["donation"] == "verified", aot
    assert aot["zero"]["donation"] == "verified", aot
    assert aot["overlap_schedule"]["issues_before_last_bwd"] >= 1, aot
