"""True multi-process rendezvous tests — the reference's process topology
(mp.spawn + gloo; SURVEY §4) done the JAX way: real OS processes,
jax.distributed coordinator, cross-process Gloo collectives."""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def test_multihost_helpers_single_process():
    import jax

    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.runtime.multihost import global_batch_from_local, process_local_rows

    mesh = make_mesh({"data": 8})
    local = np.arange(16.0).reshape(16, 1)
    arr = global_batch_from_local(mesh, local)
    assert arr.shape == (16, 1)  # 1 process: local IS global
    np.testing.assert_array_equal(np.asarray(arr), local)
    assert process_local_rows(16) == (0, 16)


@pytest.mark.slow
def test_entry_script_multiprocess_rendezvous():
    """python test_init.py --multiprocess --world-size 2 must exit 0 and
    print the reference's success line."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "test_init.py"), "--multiprocess",
         "--world-size", "2"],
        capture_output=True, text=True, timeout=180, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "successful test_setup!" in proc.stdout
    assert "psum check" in proc.stdout


@pytest.mark.slow
def test_entry_script_multiprocess_training():
    """mnist_distributed --multiprocess: 2 OS processes train data-parallel
    over jax.distributed/Gloo with cross-process grad pmean; the parent
    exits 0 and rank 0 logs decreasing loss in the reference format."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "mnist_distributed.py"), "-g", "2",
         "--multiprocess", "--epochs", "1", "--limit-steps", "6",
         "--image-size", "64", "--batch-size", "4", "--synthetic-n", "200",
         "--log-every", "2"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    losses = [
        float(line.rsplit("Loss:", 1)[1])
        for line in proc.stdout.splitlines() if "Loss:" in line
    ]
    assert len(losses) == 3, proc.stdout
    assert losses[-1] < losses[0], losses
    assert "Training complete in:" in proc.stdout
