"""Pallas 3x3 conv kernels vs the lax.conv reference (interpret on CPU).

Same strategy as the other kernel suites (test_pallas_attention,
test_pallas_bn_tail): identical call path as TPU with interpret=True,
numerical parity against the jnp/lax reference the kernel replaces —
here conv3x3_reference, the exact conv call ConvNetS2D._Conv makes.
Covers the halo rows (top/bottom edge blocks), the W-edge zero columns,
block_h fallback for non-multiple heights, bf16, and the full custom VJP
(dx through the flipped-weight fwd kernel, fused dw/db)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.ops.pallas_conv import conv3x3, conv3x3_reference


def _data(n=2, h=20, w=12, c=16, co=32, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n, h, w, c)), dtype)
    k = jnp.asarray(rng.standard_normal((3, 3, c, co)) * 0.1, dtype)
    b = jnp.asarray(rng.standard_normal((co,)), dtype)
    return x, k, b


@pytest.mark.parametrize(
    "h,w,c,co,dt,tol",
    [
        (20, 12, 16, 32, jnp.float32, 1e-5),
        (21, 9, 8, 16, jnp.float32, 1e-5),   # h=21 -> block_h fallback 3
        (20, 12, 16, 32, jnp.bfloat16, 0.03),
    ],
)
def test_forward_matches_reference(h, w, c, co, dt, tol):
    x, k, b = _data(h=h, w=w, c=c, co=co, dtype=dt)
    ref = conv3x3_reference(x, k, b)
    out = conv3x3(x, k, b, True)
    assert out.dtype == x.dtype
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=tol, atol=tol,
    )


def test_single_row_blocks_and_tiny_width():
    # h prime -> block_h 1: every block is its own top/bottom halo case
    x, k, b = _data(n=1, h=7, w=3, c=4, co=8)
    np.testing.assert_allclose(
        np.asarray(conv3x3(x, k, b, True)),
        np.asarray(conv3x3_reference(x, k, b)), rtol=1e-5, atol=1e-5,
    )


def test_grads_match_reference():
    x, k, b = _data()
    w = jnp.asarray(
        np.random.default_rng(9).standard_normal((2, 20, 12, 32)), jnp.float32
    )

    def loss_kernel(x, k, b):
        return jnp.sum(conv3x3(x, k, b, True) * w)

    def loss_ref(x, k, b):
        return jnp.sum(conv3x3_reference(x, k, b) * w)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(x, k, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, k, b)
    for a, r, name in zip(gk, gr, ("dx", "dw", "db")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-4,
            err_msg=name,
        )


def test_grads_bf16():
    """bf16 grads against the F32-computed truth: the lax.conv reference
    itself is NOT a valid bf16 oracle — XLA accumulates its reductions in
    bf16, where e.g. db = sum of 480 ones saturates at 256 (256 + 1
    rounds back to 256); the kernel accumulates in f32 and gets 480
    exactly. Kernel bf16 grads must sit within bf16 rounding of the f32
    truth."""
    x, k, b = _data(dtype=jnp.bfloat16)

    def tot(f):
        return lambda x, k, b: jnp.sum(f(x, k, b).astype(jnp.float32))

    gk = jax.grad(tot(lambda x, k, b: conv3x3(x, k, b, True)),
                  argnums=(0, 1, 2))(x, k, b)
    xf, kf, bf = (jnp.asarray(t, jnp.float32) for t in (x, k, b))
    gr = jax.grad(tot(conv3x3_reference), argnums=(0, 1, 2))(xf, kf, bf)
    for a, r, name in zip(gk, gr, ("dx", "dw", "db")):
        assert a.dtype == jnp.bfloat16, name
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(r),
            rtol=0.05, atol=0.05, err_msg=name,
        )


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
def test_stats_variant(dt):
    """conv3x3_stats: same y, and sum/sumsq equal the reductions of the
    ROUNDED output (what the BN stats pass would compute from stored y);
    grads still flow (stats cotangents are zero by contract)."""
    from tpu_sandbox.ops.pallas_conv import conv3x3_stats

    x, k, b = _data(dtype=dt)
    y, s, ss = conv3x3_stats(x, k, b, True)
    np.testing.assert_array_equal(np.asarray(y),
                                  np.asarray(conv3x3(x, k, b, True)))
    yf = np.asarray(y, np.float32).reshape(-1, y.shape[-1])
    np.testing.assert_allclose(np.asarray(s)[0], yf.sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ss)[0], (yf * yf).sum(0),
                               rtol=1e-5)

    def loss(x, k, b):
        y, s, ss = conv3x3_stats(x, k, b, True)
        return jnp.sum(y.astype(jnp.float32))

    gk = jax.grad(loss, argnums=(0, 1, 2))(x, k, b)
    gr = jax.grad(
        lambda x, k, b: jnp.sum(conv3x3(x, k, b, True).astype(jnp.float32)),
        argnums=(0, 1, 2),
    )(x, k, b)
    for a, r in zip(gk, gr):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(r))


def test_block_h_budget():
    """VMEM-budget regression pin: bh=10 at conv1-wgrad's real shape
    (W=750, 16->256) overflowed the Mosaic scoped-vmem stack (21.9 MB >
    16 MB) in the chipless AOT compile; the budget must keep the real
    ConvNet shapes at <= 4 rows while leaving tiny test shapes fast."""
    from tpu_sandbox.ops.pallas_conv import _pick_block_h

    assert _pick_block_h(750, 750, 16, 256) <= 4
    assert _pick_block_h(750, 750, 64, 128) <= 4
    assert _pick_block_h(750, 750, 128, 64) <= 4  # conv2 dgrad shape
    assert _pick_block_h(20, 12, 16, 32) == 10   # test shapes stay fast
    assert 750 % _pick_block_h(750, 750, 16, 256) == 0


def test_s2d_scattered_kernel_path():
    """The exact shapes ConvNetS2D uses: conv1's s2d-scattered 3x3 kernel
    (16->256, r=4) on a miniature image, against the reference conv."""
    from tpu_sandbox.models.convnet_s2d import scatter_kernel, space_to_depth

    rng = np.random.default_rng(3)
    img = jnp.asarray(rng.standard_normal((2, 40, 40)), jnp.float32)
    k5 = jnp.asarray(rng.standard_normal((5, 5, 1, 16)) * 0.2, jnp.float32)
    b = jnp.asarray(rng.standard_normal((16,)), jnp.float32)
    x = space_to_depth(img, 4)
    kg = scatter_kernel(k5, 4)
    bg = jnp.tile(b, 16)
    np.testing.assert_allclose(
        np.asarray(conv3x3(x, kg, bg, True)),
        np.asarray(conv3x3_reference(x, kg, bg)), rtol=1e-5, atol=1e-5,
    )
