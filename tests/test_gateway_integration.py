"""Gateway end to end against real replica processes (slow).

The tier-1 file (test_gateway.py) runs the gateway over real sockets but
with in-process stub-step replicas. This file closes the remaining gaps:

- the replica-death kill matrix: a request routed to a replica that is
  then SIGKILLed mid-load must still terminate with exactly one verdict,
  rescued by the client's retry/hedge path or a peer's scavenge — the
  gateway's targeted routing is a hint, never a trap;
- the gateway's own process entrypoint (``python -m
  tpu_sandbox.gateway.server``), hello auth over the printed port, and a
  clean SIGTERM shutdown;
- the full ``bench.py --metric gateway --quick`` CLI in a fresh
  interpreter (the tier-1 smoke calls bench_gateway in-process).

Real subprocesses + cold jax compiles: slow-marked, out of tier-1.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent

REPLICA_CFG = {
    "cache": {"num_blocks": 24, "block_size": 4, "max_blocks_per_seq": 8},
    "max_batch": 3,
    "buckets": [8, 16],
    "param_seed": 0,
    "lease_ttl": 1.0,
    "timeout": 240.0,
}

N_REQUESTS = 30


def _replica_env(kv_port):
    from tpu_sandbox.runtime.supervisor import ENV_KV_PORT

    return {
        **os.environ,
        ENV_KV_PORT: str(kv_port),
        "JAX_PLATFORMS": "cpu",
        "JAX_THREEFRY_PARTITIONABLE": "1",
        "PYTHONPATH": str(REPO) + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
    }


def _spawn_replica(kv_port, tag):
    return subprocess.Popen(
        [sys.executable, "-m", "tpu_sandbox.serve.replica",
         "--config", json.dumps(REPLICA_CFG), "--tag", tag],
        env=_replica_env(kv_port), cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def test_replica_kill_mid_load_every_request_verdicts_once():
    import numpy as np

    from tpu_sandbox.gateway.client import GatewayClient
    from tpu_sandbox.gateway.fleet import FleetSpec
    from tpu_sandbox.gateway.server import Gateway
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve import replica as R

    rng = np.random.default_rng(0)
    server = KVServer()
    kv = KVClient(port=server.port)
    procs = []
    try:
        procs = [_spawn_replica(server.port, f"p{i}") for i in range(2)]
        gw = Gateway(kv, [FleetSpec(block_size=4, service_rate_rps=50.0)],
                     refresh_min_s=0.01).start()
        client = GatewayClient(gw.port, max_retries=2, hedge_after=2.0)
        try:
            # wait out the cold compiles: both replicas reporting
            deadline = time.monotonic() + 180
            while len(R.read_load_reports(kv)) < 2:
                assert time.monotonic() < deadline, "replicas never reported"
                for p in procs:
                    assert p.poll() is None, p.communicate()[0]
                time.sleep(0.1)

            rids = []
            for i in range(N_REQUESTS):
                rid = f"r{i}"
                prompt = [int(t) for t in
                          rng.integers(1, 64, size=int(rng.integers(4, 13)))]
                assert client.submit(rid, prompt, int(rng.integers(4, 9)))
                rids.append(rid)
            R.announce_total(kv, N_REQUESTS)

            # kill replica 1 once the fleet is demonstrably mid-load
            while len(kv.keys("serve/result/")) < 3:
                assert time.monotonic() < deadline, "no results before kill"
                time.sleep(0.02)
            n_at_kill = len(kv.keys("serve/result/"))
            assert n_at_kill < N_REQUESTS, "too fast: no mid-load window"
            procs[1].kill()

            verdicts = {rid: client.result(rid, timeout=180.0)
                        for rid in rids}
        finally:
            client.close()
            gw.close()

        # exactly one terminal verdict each, none lost to the kill
        assert set(verdicts) == set(rids)
        for rid, v in verdicts.items():
            assert v["verdict"] in ("ok", "SHED"), (rid, v)
            if v["verdict"] == "ok":
                assert len(v["tokens"]) >= 1, (rid, v)
        by_replica = {v["replica"] for v in verdicts.values()
                      if v["verdict"] == "ok"}
        assert "p0" in by_replica, "survivor served nothing"
        # the rescue machinery ran: the killed replica's stranded requests
        # come back via client retries/hedges or a peer scavenge requeueing
        # them onto the shared queue — some combination must have fired
        rescued = (client.stats.retries + client.stats.hedges
                   + int(kv.try_get(R.K_TAIL) or b"0"))
        assert rescued > 0, "kill mid-load exercised no rescue path"
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
            p.stdout.close()
        kv.close()
        server.stop()


def test_gateway_process_entrypoint_serves_and_shuts_down():
    from tpu_sandbox.gateway.client import GatewayAuthError, GatewayClient
    from tpu_sandbox.runtime.kvstore import KVServer

    server = KVServer()
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_sandbox.gateway",
         "--kv-port", str(server.port), "--token", "sesame"],
        env=_replica_env(server.port), cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        line = proc.stdout.readline()
        assert "listening on" in line, line
        port = int(line.split("listening on ")[1].split()[0].rsplit(":", 1)[1])
        with GatewayClient(port, token="sesame") as c:
            stats = c.gateway_stats()
            assert stats["admission"] == "feasible"
        with pytest.raises(GatewayAuthError):
            GatewayClient(port, token="wrong")
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
        rest = proc.stdout.read()
        assert "closed" in rest, rest
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        proc.stdout.close()
        server.stop()


def test_bench_gateway_cli_prints_one_json_line():
    """`bench.py --metric gateway --quick` end to end in a fresh
    interpreter. Quick mode is too small for the perf claims to be
    meaningful, so only their presence and the accounting invariants are
    asserted; BENCH_r08.json holds a committed full run."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"),
         "--metric", "gateway", "--quick"],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "gateway"
    assert out["every_request_verdicted"] is True
    assert "prefix_beats_random_p99" in out
    assert "feasible_goodput_holds" in out
    for arm in ("routing_prefix", "routing_random",
                "admission_feasible", "admission_occupancy"):
        assert out[arm]["verdict_audit_ok"] is True
