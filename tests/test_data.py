"""Data-layer tests: IDX reader round-trip, synthetic dataset determinism/
learnability, DistributedSampler torch-parity structure, BatchLoader."""

import gzip
import struct

import numpy as np
import pytest

from tpu_sandbox.data import BatchLoader, DistributedSampler, load_mnist, synthetic_mnist
from tpu_sandbox.data.mnist import normalize


def write_idx(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, arr.ndim))
        f.write(struct.pack(f">{arr.ndim}I", *arr.shape))
        f.write(arr.tobytes())


def test_idx_reader_roundtrip(tmp_path):
    images = np.arange(3 * 28 * 28, dtype=np.uint8).reshape(3, 28, 28)
    labels = np.array([1, 2, 3], dtype=np.uint8)
    write_idx(tmp_path / "train-images-idx3-ubyte", images)
    write_idx(tmp_path / "train-labels-idx1-ubyte", labels)
    got_i, got_l = load_mnist("train", tmp_path)
    np.testing.assert_array_equal(got_i, images)
    np.testing.assert_array_equal(got_l, labels)


def test_idx_reader_gzip(tmp_path):
    labels = np.array([7], dtype=np.uint8)
    images = np.zeros((1, 28, 28), dtype=np.uint8)
    for stem, arr in [("t10k-images-idx3-ubyte", images), ("t10k-labels-idx1-ubyte", labels)]:
        raw = struct.pack(">HBB", 0, 0x08, arr.ndim) + struct.pack(
            f">{arr.ndim}I", *arr.shape
        ) + arr.tobytes()
        with gzip.open(tmp_path / (stem + ".gz"), "wb") as f:
            f.write(raw)
    got_i, got_l = load_mnist("test", tmp_path)
    assert got_i.shape == (1, 28, 28) and got_l[0] == 7


def test_load_mnist_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="synthetic_mnist"):
        load_mnist("train", tmp_path / "nope")
    with pytest.raises(ValueError, match="split"):
        load_mnist("validation", tmp_path)


def test_synthetic_deterministic_and_classy():
    i1, l1 = synthetic_mnist(n=256, seed=0)
    i2, l2 = synthetic_mnist(n=256, seed=0)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_array_equal(l1, l2)
    assert i1.shape == (256, 28, 28) and i1.dtype == np.uint8
    # classes must be separable: same-class images closer than cross-class
    x = normalize(i1).reshape(256, -1)
    c0, c1 = x[l1 == 0], x[l1 == 1]
    if len(c0) > 1 and len(c1) > 0:
        intra = np.linalg.norm(c0[0] - c0[1])
        inter = np.linalg.norm(c0[0] - c1[0])
        assert inter > intra


def test_normalize():
    out = normalize(np.full((2, 28, 28), 255, np.uint8))
    assert out.shape == (2, 28, 28, 1) and out.dtype == np.float32
    assert out.max() == 1.0


def test_sampler_partitions_cover_and_disjoint():
    s = [DistributedSampler(103, num_replicas=4, rank=r) for r in range(4)]
    parts = [set(x.indices(0).tolist()) for x in s]
    assert all(len(p) == 26 for p in parts)  # ceil(103/4)
    union = set().union(*parts)
    assert union == set(range(103))  # padding wraps, so all covered


def test_sampler_epoch_reshuffle_and_quirk():
    s = DistributedSampler(100, num_replicas=2, rank=0)
    a, b = s.indices(0), s.indices(1)
    assert not np.array_equal(a, b)  # set_epoch changes order
    np.testing.assert_array_equal(a, s.indices(0))  # reference quirk: epoch 0 reused


def test_sampler_matches_torch_structure():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DistributedSampler as TorchSampler

    tds = TorchSampler(range(103), num_replicas=4, rank=2, shuffle=False)
    ours = DistributedSampler(103, num_replicas=4, rank=2, shuffle=False)
    np.testing.assert_array_equal(np.fromiter(iter(tds), int), ours.indices())


def test_sampler_validates_rank():
    with pytest.raises(ValueError, match="rank"):
        DistributedSampler(10, num_replicas=2, rank=2)


def test_batch_loader_shapes_and_partial_batch():
    images, labels = synthetic_mnist(n=23)
    loader = BatchLoader(images, labels, batch_size=5)
    batches = list(loader)
    assert len(batches) == 5 == len(loader)
    assert batches[0][0].shape == (5, 28, 28)
    assert batches[-1][0].shape == (3, 28, 28)  # drop_last=False keeps it
    loader2 = BatchLoader(images, labels, batch_size=5, drop_last=True)
    assert len(list(loader2)) == 4 == len(loader2)


def test_batch_loader_shuffle_reproducible():
    images, labels = synthetic_mnist(n=50)
    l1 = BatchLoader(images, labels, batch_size=10, shuffle=True, seed=0)
    l2 = BatchLoader(images, labels, batch_size=10, shuffle=True, seed=0)
    np.testing.assert_array_equal(next(iter(l1))[1], next(iter(l2))[1])
    l1.set_epoch(1)
    assert not np.array_equal(next(iter(l1))[1], next(iter(l2))[1])


def test_batch_loader_with_sampler_shards():
    images, labels = synthetic_mnist(n=40)
    loaders = [
        BatchLoader(
            images, labels, 5,
            sampler=DistributedSampler(40, num_replicas=2, rank=r),
        )
        for r in range(2)
    ]
    seen = [np.concatenate([b[1] for b in ld]) for ld in loaders]
    assert len(seen[0]) == len(seen[1]) == 20
    with pytest.raises(ValueError, match="mutually exclusive"):
        BatchLoader(images, labels, 5, shuffle=True,
                    sampler=DistributedSampler(40, num_replicas=2, rank=0))
