"""Flash-ring attention (Pallas per-block forward + hand-written ring
backward) vs the reference math and the jnp ring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.ops.attention import causal_attention
from tpu_sandbox.parallel.flash_ring import make_flash_ring_attention
from tpu_sandbox.parallel.ring_attention import make_ring_attention
from tpu_sandbox.runtime.mesh import make_mesh


def qkv(b=2, s=32, h=2, d=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape) for k in ks)


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh({"sp": 8})


def test_offset_lse_partials_merge_to_reference():
    """flash_attention_lse with offsets: two half-sequence partials merged
    by their logsumexps must equal full attention — the identity the ring
    forward is built on."""
    from tpu_sandbox.ops.pallas_attention import flash_attention_lse
    from tpu_sandbox.parallel.flash_ring import _merge, _NEG

    q, k, v = qkv(s=64, seed=4)
    half = 32
    ref = causal_attention(q, k, v, causal=True)

    o = jnp.zeros((*q.shape[:1], 64, *q.shape[2:]), jnp.float32)
    lse = jnp.full((q.shape[0], 64, q.shape[2]), _NEG, jnp.float32)
    for blk in range(2):
        o_b, lse_b = flash_attention_lse(
            q, k[:, blk * half:(blk + 1) * half],
            v[:, blk * half:(blk + 1) * half],
            causal=True, q_offset=0, kv_offset=blk * half, interpret=True,
        )
        o, lse = _merge(o, lse, o_b, lse_b)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_ring_matches_reference(sp_mesh, causal):
    q, k, v = qkv(seed=1)
    ref = causal_attention(q, k, v, causal=causal)
    out = make_flash_ring_attention(sp_mesh, "sp", causal=causal,
                                    interpret=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_ring_gradients_match_reference(sp_mesh):
    q, k, v = qkv(seed=2)
    w = jax.random.normal(jax.random.key(9), q.shape)

    fr = make_flash_ring_attention(sp_mesh, "sp", causal=True, interpret=True)

    def loss_fr(q, k, v):
        return jnp.sum(fr(q, k, v) * w)

    def loss_ref(q, k, v):
        return jnp.sum(causal_attention(q, k, v, causal=True) * w)

    g_fr = jax.grad(loss_fr, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_fr, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"grad d{name}",
        )


def test_flash_ring_matches_jnp_ring(sp_mesh):
    q, k, v = qkv(seed=3)
    ring = make_ring_attention(sp_mesh, "sp", causal=True)(q, k, v)
    flash = make_flash_ring_attention(sp_mesh, "sp", causal=True,
                                      interpret=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(ring), atol=2e-5)


def test_seq_parallel_flash_ring_trains_like_ring():
    import optax

    from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
    from tpu_sandbox.parallel import SeqParallel

    cfg = TransformerConfig(vocab_size=16, d_model=16, n_heads=2, n_layers=2,
                            d_ff=32, max_len=32)
    mesh = make_mesh({"data": 2, "sp": 4})
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 16, size=(4, 32)).astype(np.int32)
    targets = ((tokens + 1) % 16).astype(np.int32)

    losses = {}
    for attn in ("ring", "flash_ring"):
        eng = SeqParallel(lambda a: TransformerLM(cfg, attention_fn=a),
                          optax.sgd(1e-2), mesh, attn=attn, donate=False)
        state = eng.shard_state(eng.init_state(jax.random.key(0),
                                               jnp.asarray(tokens)))
        _, loss = eng.train_step(state, *eng.shard_batch(tokens, targets))
        losses[attn] = float(np.asarray(loss))
    np.testing.assert_allclose(losses["ring"], losses["flash_ring"],
                               rtol=1e-5)
