"""Fast-fabric tier-1: ZB-H1 schedules, measured autotuning, the device
transport, and chunk-streamed npz staging.

The load-bearing claims, each pinned here at process-free scale (the
process-level twins live in tests/test_mpmd_integration.py):

- ZB-H1 op lists split the backward into B (grad-input) and W
  (grad-weight) without raising the activation-stash bound above 1F1B's,
  and training under them is BITWISE equal on params to the fused
  backward — schedules move work, never values.
- ``simulate_step`` reproduces the analytic 1F1B bubble on uniform costs
  and predicts ZB-H1 below it; ``autotune_plan`` picks from measured
  per-stage op costs.
- ``DeviceTransport`` keeps the produce-once/claim-once contract of the
  host wires (the journal is authoritative) while serving gets from the
  published device buffers; a bufferless rebuild falls back to journal
  bytes bitwise.
- ``stream_load_npz`` returns arrays bitwise equal to ``np.load``'s for
  every dtype/order/compression shape we ship.
"""

import os

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM  # noqa: E402
from tpu_sandbox.mpmd.driver import MPMDPipeline  # noqa: E402
from tpu_sandbox.mpmd.program import check_layer_split  # noqa: E402
from tpu_sandbox.mpmd.schedule import (  # noqa: E402
    autotune_plan,
    bubble_fraction,
    max_in_flight,
    one_f_one_b,
    ops_for,
    simulate_step,
    zb_h1,
)
from tpu_sandbox.mpmd.transport import (  # noqa: E402
    DeviceTransport,
    LocalTransport,
    iter_chunks,
    pack_arrays,
    pack_views,
    unpack_arrays,
)
from tpu_sandbox.runtime.staging import stream_load_npz  # noqa: E402

CFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=4,
                        d_ff=64, max_len=128)


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_stages,microbatches", [(2, 4), (3, 4), (3, 8),
                                                   (4, 2), (4, 16)])
def test_zb_h1_op_list_is_complete_and_ordered(n_stages, microbatches):
    for s in range(n_stages):
        ops = zb_h1(s, n_stages, microbatches)
        by_op = {}
        for op, m in ops:
            by_op.setdefault(op, []).append(m)
        # every microbatch gets exactly one F, one B, one W
        for op in ("F", "B", "W"):
            assert sorted(by_op[op]) == list(range(microbatches)), (s, op)
        # per-microbatch order is F before B before W
        for m in range(microbatches):
            fi = ops.index(("F", m))
            bi = ops.index(("B", m))
            wi = ops.index(("W", m))
            assert fi < bi < wi, (s, m)


def _activation_stash_peak(ops):
    """Peak microbatches forwarded but not yet through B — the
    activation-stash bound proper (W holds only the (input, cotangent)
    pair, which is the bounded extra state the schedule docstring
    documents)."""
    live = peak = 0
    for op, _m in ops:
        if op == "F":
            live += 1
        elif op == "B":
            live -= 1
        peak = max(peak, live)
    return peak


@pytest.mark.parametrize("n_stages,microbatches", [(2, 4), (3, 4), (3, 8),
                                                   (4, 16)])
def test_zb_h1_stash_bounds_match_1f1b(n_stages, microbatches):
    """ZB-H1 is the memory-neutral variant: the activation stash (held
    F -> B) never exceeds 1F1B's, and the deferred (input, cotangent)
    pairs for W are bounded by the warmup reserve + the one in hand."""
    for s in range(n_stages):
        zb = zb_h1(s, n_stages, microbatches)
        fused = one_f_one_b(s, n_stages, microbatches)
        assert _activation_stash_peak(zb) == _activation_stash_peak(fused)
        warmup = min(microbatches, n_stages - 1 - s)
        assert (max_in_flight(zb) - max_in_flight(fused)) <= warmup + 1


def test_ops_for_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown schedule kind"):
        ops_for("gpipe", 0, 2, 4)


def test_simulate_step_reproduces_analytic_1f1b_bubble():
    """Uniform F=B costs, no wire: the simulated 1F1B bubble is the
    closed-form (S-1)/(M+S-1) the analytic gauge promises."""
    S, M = 3, 4
    ops = {s: one_f_one_b(s, S, M) for s in range(S)}
    costs = {s: {"F": 1.0, "B": 1.0} for s in range(S)}
    sim = simulate_step(ops, costs)
    assert sim["bubble_max"] == pytest.approx(bubble_fraction(S, M), abs=1e-9)


def test_simulate_step_zb_h1_beats_1f1b_on_split_costs():
    """With the backward split in half, ZB-H1's drain-phase W fill
    drops the simulated bubble below fused 1F1B's."""
    S, M = 3, 4
    fused = simulate_step({s: one_f_one_b(s, S, M) for s in range(S)},
                          {s: {"F": 1.0, "B": 1.0} for s in range(S)})
    split = simulate_step({s: zb_h1(s, S, M) for s in range(S)},
                          {s: {"F": 1.0, "B": 0.5, "W": 0.5}
                           for s in range(S)})
    assert split["step_seconds"] < fused["step_seconds"]
    assert split["bubble_mean"] < fused["bubble_mean"]


def test_simulate_step_detects_deadlock():
    # stage 0's B waits on stage 1's B, which never runs
    ops = {0: [("B", 0)], 1: [("F", 0)]}
    costs = {0: {"B": 1.0}, 1: {"F": 1.0}}
    with pytest.raises(RuntimeError, match="deadlock"):
        simulate_step(ops, costs)


def test_autotune_plan_prefers_zb_and_reports_frontier():
    S = 3
    measured = {s: {"F": 0.01, "B": 0.005, "W": 0.005, "A": 0.002}
                for s in range(S)}
    # at small M the drain dominates and ZB-H1 strictly wins; at large M
    # the steady phase saturates either way and the kinds tie (argmin
    # tie-breaks to the simpler 1f1b), so candidates stay small here
    plan = autotune_plan(measured, n_stages=S, measured_microbatches=4,
                         candidates=(2, 4))
    assert plan["kind"] == "zb_h1"
    # the whole frontier rides along: every (kind, M) candidate priced
    assert len(plan["candidates"]) == 2 * 2
    assert all({"kind", "microbatches", "predicted_step_s",
                "predicted_bubble"} <= set(r) for r in plan["candidates"])
    best = plan["predicted"]
    assert all(best["predicted_step_s"] <= r["predicted_step_s"]
               for r in plan["candidates"])


# ---------------------------------------------------------------------------
# uneven layer splits
# ---------------------------------------------------------------------------


def test_check_layer_split_validates():
    assert check_layer_split(8, 4, None) == [2, 2, 2, 2]
    assert check_layer_split(8, 3, [4, 3, 1]) == [4, 3, 1]
    with pytest.raises(ValueError, match="layer_split"):
        check_layer_split(8, 3, None)  # not divisible: must be explicit
    with pytest.raises(ValueError):
        check_layer_split(8, 3, [4, 4])  # wrong length
    with pytest.raises(ValueError):
        check_layer_split(8, 3, [4, 3, 2])  # wrong sum
    with pytest.raises(ValueError):
        check_layer_split(8, 3, [8, 0, 0])  # empty stage


# ---------------------------------------------------------------------------
# transport: chunk iteration + the device tier
# ---------------------------------------------------------------------------


def _sample_arrays():
    rng = np.random.default_rng(7)
    return [
        rng.standard_normal((13, 5)).astype(np.float32),
        np.arange(11, dtype=np.int32),
        rng.standard_normal(()).astype(np.float64),
        np.zeros((0, 4), np.float32),
    ]


def test_iter_chunks_matches_joined_payload():
    arrays = _sample_arrays()
    meta, views = pack_views(arrays)
    _meta2, payload = pack_arrays(arrays)
    for chunk_bytes in (1, 7, 64, 1 << 20):
        chunks = list(iter_chunks(views, chunk_bytes))
        assert all(len(c) <= chunk_bytes for c in chunks)
        assert b"".join(chunks) == payload
    back = unpack_arrays(meta, payload)
    for a, b in zip(arrays, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert np.array_equal(a, b)


def test_device_transport_contract():
    tr = DeviceTransport()
    arrays = [jnp.arange(6, dtype=jnp.float32).reshape(2, 3)]
    assert tr.put("e", 0, 0, arrays) is True
    assert tr.put("e", 0, 0, arrays) is False  # produce-once via journal
    assert tr.poll("e", 0, 0)
    assert tr.claim("e", 0, 0, generation=0) is True
    assert tr.claim("e", 0, 0, generation=0) is False  # claim-once
    assert tr.claim("e", 0, 0, generation=1) is True   # new generation
    (got,) = tr.get("e", 0, 0, timeout=1.0)
    assert np.array_equal(np.asarray(got), np.asarray(arrays[0]))
    assert tr.stats.device_hits == 1
    assert tr.stats.journal_fallbacks == 0
    # the journal recorded the same slot durably
    assert tr.journal.poll("e", 0, 0)
    audit = tr.audit()
    assert audit["commits"]["e/0/0"] == 2  # both put attempts counted


def test_device_transport_journal_fallback_is_bitwise():
    """A transport rebuilt over a persisted journal (driver crash: the
    device buffers are gone) serves journal bytes — bitwise what the
    buffer held."""
    journal = LocalTransport()
    tr = DeviceTransport(journal)
    x = np.random.default_rng(3).standard_normal((4, 4)).astype(np.float32)
    tr.put("e", 1, 0, [x])
    rebuilt = DeviceTransport(journal)  # no buffers, same journal
    (got,) = rebuilt.get("e", 1, 0, timeout=1.0)
    assert got.tobytes() == x.tobytes()
    assert rebuilt.stats.journal_fallbacks == 1
    assert rebuilt.stats.device_hits == 0


def test_device_transport_release_step_clears_both_tiers():
    tr = DeviceTransport()
    tr.put("e", 0, 0, [np.zeros(3, np.float32)])
    tr.put("e", 1, 0, [np.ones(3, np.float32)])
    tr.release_step("e", 0)
    assert not tr.poll("e", 0, 0)
    assert not tr.journal.poll("e", 0, 0)
    assert tr.poll("e", 1, 0)  # later steps untouched


def test_device_transport_get_timeout():
    tr = DeviceTransport()
    with pytest.raises(TimeoutError):
        tr.get("never", 0, 0, timeout=0.05)


# ---------------------------------------------------------------------------
# streamed npz staging
# ---------------------------------------------------------------------------


def test_stream_load_npz_bitwise_vs_np_load(tmp_path):
    rng = np.random.default_rng(11)
    trees = {
        "f32": rng.standard_normal((17, 9)).astype(np.float32),
        "f64_scalar": rng.standard_normal(()),
        "i8": rng.integers(-100, 100, size=(33,), dtype=np.int8),
        "bools": rng.integers(0, 2, size=(5, 5)).astype(bool),
        "empty": np.zeros((0, 3), np.float32),
        "fortran": np.asfortranarray(
            rng.standard_normal((12, 7)).astype(np.float32)),
    }
    for name, saver in (("plain.npz", np.savez),
                        ("compressed.npz", np.savez_compressed)):
        path = tmp_path / name
        saver(path, **trees)
        streamed = stream_load_npz(path, chunk_bytes=64)  # force chunking
        with np.load(path) as z:
            assert sorted(streamed) == sorted(z.files)
            for k in z.files:
                ref = z[k]
                got = streamed[k]
                assert got.dtype == ref.dtype and got.shape == ref.shape
                assert got.tobytes() == ref.tobytes(), (name, k)


def test_stream_load_npz_only_filter(tmp_path):
    path = tmp_path / "s.npz"
    np.savez(path, a=np.arange(4), b=np.arange(8))
    out = stream_load_npz(path, only={"b"})
    assert sorted(out) == ["b"]
    assert np.array_equal(out["b"], np.arange(8))


def test_stream_load_npz_rejects_object_arrays(tmp_path):
    path = tmp_path / "obj.npz"
    np.savez(path, bad=np.array([{"a": 1}], dtype=object), allow_pickle=True)
    with pytest.raises(ValueError, match="object"):
        stream_load_npz(path)


# ---------------------------------------------------------------------------
# ZB-H1 end-to-end parity (in-process, 3 uneven stages, device transport)
# ---------------------------------------------------------------------------


def _train(kind, transport, layer_split, steps=3, microbatches=4):
    tx = optax.sgd(0.1)
    pipe = MPMDPipeline(CFG, tx, n_stages=3, microbatches=microbatches,
                        transport=transport, kind=kind,
                        layer_split=layer_split)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, CFG.vocab_size, size=(8, 16)).astype(np.int32)
    targets = ((tokens + 7) % CFG.vocab_size).astype(np.int32)
    flat = jax.tree.map(
        np.asarray,
        TransformerLM(CFG).init(jax.random.key(0), tokens)["params"])
    pipe.init_from_flat(flat)
    losses = pipe.train(steps, tokens, targets)
    return pipe, losses


def test_zb_h1_grad_parity_vs_fused_backward():
    """The tentpole numerics claim: ZB-H1's per-layer split backward is
    the same math as the fused 1F1B backward — losses and params agree
    to float32 ulps (NOT bitwise — the per-layer vjps compile as
    separate XLA units whose reduction grouping differs from the fused
    scan transpose). Bitwise ZB determinism, which is what
    replay-after-fault leans on, is the slow twin test below."""
    split = [2, 1, 1]  # uneven on purpose: stage 0 is the heavy one
    fused_pipe, fused_losses = _train("1f1b", LocalTransport(), split)
    zb_pipe, zb_losses = _train("zb_h1", DeviceTransport(), split)
    assert zb_losses == pytest.approx(fused_losses, abs=1e-6)
    ref = fused_pipe.merged_params()
    got = zb_pipe.merged_params()
    ref_leaves = jax.tree.leaves(ref)
    got_leaves = jax.tree.leaves(got)
    assert len(ref_leaves) == len(got_leaves)
    for a, b in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6)
    # the device tier actually carried the traffic
    assert zb_pipe.transport.stats.device_hits > 0
    assert zb_pipe.transport.stats.journal_fallbacks == 0
    # and the measured costs feed a well-formed autotuned plan: stage 0
    # times no B (its grad-input is never shipped anywhere — all its
    # backward work is W), the last stage no F (fused into loss B)
    costs = zb_pipe.measured_op_costs()
    assert {"F", "W", "A"} <= set(costs[0]) and "B" not in costs[0]
    assert {"F", "B", "W", "A"} <= set(costs[1])
    assert {"B", "W", "A"} <= set(costs[2]) and "F" not in costs[2]
    plan = autotune_plan(costs, n_stages=3, measured_microbatches=4,
                         candidates=(2, 4, 8))
    assert plan["kind"] in ("1f1b", "zb_h1")
    assert plan["microbatches"] in (2, 4, 8)


@pytest.mark.slow
def test_zb_h1_rerun_is_bitwise_deterministic():
    """Same split programs, same data, twice over -> bitwise-equal
    params. This is the guarantee replay-after-fault actually leans on
    (a respawned stage re-runs the SAME compiled B/W programs, only
    interleaved differently)."""
    split = [2, 1, 1]
    pipe_a, losses_a = _train("zb_h1", DeviceTransport(), split)
    pipe_b, losses_b = _train("zb_h1", DeviceTransport(), split)
    assert losses_a == losses_b
    for a, b in zip(jax.tree.leaves(pipe_a.merged_params()),
                    jax.tree.leaves(pipe_b.merged_params())):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
