"""End-to-end cross-host elastic training (CPU, 2 simulated hosts x 1 rank):
the AgentLauncher plays cluster scheduler, per-host agents elect a leader
over the KV store, and the three failure modes the architecture exists for
each recover to bitwise parity with an unfaulted same-seed run:

- leader death  (kill_agent on rank 0's agent) — the job survives losing
  the very process driving it; the restart is charged exactly once
- host death    (kill_agent on a follower's agent) — respawned agent
  reports its lost ranks instead of waiting out a heartbeat timeout
- partition     (partition_host) — ranks keep running but their agent goes
  silent; only agent-level heartbeats can see it, leadership moves to a
  live host (term 2), and the healed host is deposed + torn down before
  the next generation starts

Real subprocesses + jax.distributed per generation: slow-marked, out of
tier-1. The control-plane mechanics are covered fast in test_host_agent.py
and test_election.py.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "mnist_distributed.py"

# 64 synthetic samples / (bs 4 x 2 ranks) = 8 steps per epoch, 16 total
COMMON = [
    "--elastic", "--agents", "2", "-g", "2", "--epochs", "2",
    "--batch-size", "4", "--image-size", "28", "--synthetic-n", "64",
    "--limit-steps", "8", "--dtype", "fp32", "--plan", "plain",
    "--log-every", "1000", "--ckpt-every", "2",
]
TOTAL_STEPS = 16


def run_agents(ckpt_dir, fault_plan=None, timeout=600, extra_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_SANDBOX_BACKOFF"] = "0.1"
    env["TPU_SANDBOX_TERM_TIMEOUT"] = "10"
    env["TPU_SANDBOX_LEASE_TTL"] = "2"
    env["TPU_SANDBOX_AGENT_TIMEOUT"] = "4"
    env.update(extra_env or {})
    if fault_plan is not None:
        env["TPU_SANDBOX_FAULT_PLAN"] = json.dumps(fault_plan)
    cmd = [sys.executable, str(SCRIPT), *COMMON, "--ckpt-dir", str(ckpt_dir)]
    return subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


def final_params(ckpt_dir):
    f = Path(ckpt_dir) / f"step-{TOTAL_STEPS:08d}.npz"
    assert f.exists(), f"missing final checkpoint {f}"
    with np.load(f, allow_pickle=False) as z:
        return {k: z[k].copy() for k in z.files if k.startswith("leaf:")}


def assert_same_model(a, b):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=0, atol=1e-6, err_msg=k)


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """One unfaulted run shared by every parity assertion below."""
    ref_dir = tmp_path_factory.mktemp("mh") / "ref"
    r = run_agents(ref_dir)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 generation(s)" in r.stdout
    assert "elected leader (term 1)" in r.stdout
    return final_params(ref_dir)


def test_leader_death_fails_over_and_resumes(reference, tmp_path):
    """Rank 0's agent — the leader — is SIGKILLed at step 5. pdeathsig
    takes its rank down too. Whoever leads next (the respawned agent
    re-acquiring its still-live lease, or agent 1 stealing at term 2)
    reconstructs the generation state from the store, charges exactly one
    restart, and gen 2 resumes from the last checkpoint."""
    d = tmp_path / "leader_death"
    r = run_agents(
        d, fault_plan=[{"rank": 0, "step": 5, "action": "kill_agent"}]
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert "fault: kill_agent" in out, out
    assert "respawning [1/" in out, out                 # scheduler replaced it
    assert "agent restarted; local ranks lost" in out, out
    assert "1 restart(s) charged" in out, out           # charged exactly once
    assert "resumed from step 4" in out, out            # ckpt_every=2, kill at 5
    assert "2 generation(s)" in out, out
    assert_same_model(reference, final_params(d))


def test_host_death_charged_once(reference, tmp_path):
    """A follower host dies (agent + its rank). The leader keeps the
    lease, the launcher replaces the host, and the replacement reports its
    lost ranks immediately instead of letting the rank heartbeat timeout
    (60s default) expire."""
    d = tmp_path / "host_death"
    r = run_agents(
        d, fault_plan=[{"rank": 1, "step": 5, "action": "kill_agent"}]
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert "agent restarted; local ranks lost" in out, out
    assert "1 restart(s) charged" in out, out
    assert "0 preemption(s)" in out, out
    assert out.count("elected leader") >= 1, out
    assert_same_model(reference, final_params(d))


def test_partition_detected_within_heartbeat_timeout(reference, tmp_path):
    """Rank 0's agent goes silent toward the store for 8s while its rank
    keeps training — the failure only agent-level heartbeats can see.
    Agent 1 must steal the lease (term 2), flag the silent host with a
    bounded stamp age, and gate the relaunch until the healed host has
    acked the teardown (no zombie ranks in gen 2)."""
    d = tmp_path / "partition"
    r = run_agents(
        d,
        fault_plan=[{"rank": 0, "step": 5, "action": "partition_host",
                     "target": "8"}],
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert "fault: partition_host" in out, out
    assert "elected leader (term 2)" in out, out        # true failover
    assert "silent for >4.0s" in out, out
    # detection latency is bounded: the frozen stamp's age at detection
    # must sit between the timeout and the partition duration
    age = float(out.split("stamp ages {0: ")[1].split("}")[0])
    assert 4.0 <= age <= 8.0, out
    assert "partition healed; rejoining the control plane" in out, out
    assert "deposed" in out, out                        # stale leader fenced
    assert "1 restart(s) charged" in out, out
    assert "2 generation(s)" in out, out
    assert_same_model(reference, final_params(d))
