"""Transposed-layout Pallas conv (ops/pallas_conv_t.py) vs the lax.conv
reference (interpret on CPU) — same strategy as test_pallas_conv: the
TPU call path with interpret=True, numerical parity against
conv3x3_t_reference (transpose -> the exact NHWC conv -> transpose).
Covers halo rows, W-edge zero columns, block_h fallback, bf16, the full
custom VJP, the stats variant, and layout round-trip against the NHWC
kernel on the s2d-scattered shapes ConvNetS2D uses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.ops.pallas_conv_t import (
    conv3x3_t,
    conv3x3_t_reference,
    conv3x3_t_stats,
)


def _data(n=2, h=20, w=12, c=16, co=32, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n, h, c, w)), dtype)
    k = jnp.asarray(rng.standard_normal((3, 3, c, co)) * 0.1, dtype)
    b = jnp.asarray(rng.standard_normal((co,)), dtype)
    return x, k, b


@pytest.mark.parametrize(
    "h,w,c,co,dt,tol",
    [
        (20, 12, 16, 32, jnp.float32, 1e-5),
        (21, 9, 8, 16, jnp.float32, 1e-5),   # h=21 -> block_h fallback 3
        (20, 12, 16, 32, jnp.bfloat16, 0.03),
    ],
)
def test_forward_matches_reference(h, w, c, co, dt, tol):
    x, k, b = _data(h=h, w=w, c=c, co=co, dtype=dt)
    ref = conv3x3_t_reference(x, k, b)
    out = conv3x3_t(x, k, b, True)
    assert out.dtype == x.dtype
    assert out.shape == (x.shape[0], h, co, w)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=tol, atol=tol,
    )


def test_single_row_blocks_and_tiny_width():
    x, k, b = _data(n=1, h=7, w=3, c=4, co=8)
    np.testing.assert_allclose(
        np.asarray(conv3x3_t(x, k, b, True)),
        np.asarray(conv3x3_t_reference(x, k, b)), rtol=1e-5, atol=1e-5,
    )


def test_grads_match_reference():
    x, k, b = _data()
    w = jnp.asarray(
        np.random.default_rng(9).standard_normal((2, 20, 32, 12)),
        jnp.float32,
    )

    def loss_kernel(x, k, b):
        return jnp.sum(conv3x3_t(x, k, b, True) * w)

    def loss_ref(x, k, b):
        return jnp.sum(conv3x3_t_reference(x, k, b) * w)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(x, k, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, k, b)
    for a, r, name in zip(gk, gr, ("dx", "dw", "db")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-4,
            err_msg=name,
        )


@pytest.mark.slow  # tier-1 keeps test_pallas_conv.py::test_grads_bf16
def test_grads_bf16():
    """bf16 grads against the F32-computed truth (the lax.conv reference
    accumulates in bf16 and is not a valid oracle — see test_pallas_conv
    ::test_grads_bf16)."""
    x, k, b = _data(dtype=jnp.bfloat16)

    def tot(f):
        return lambda x, k, b: jnp.sum(f(x, k, b).astype(jnp.float32))

    gk = jax.grad(tot(lambda x, k, b: conv3x3_t(x, k, b, True)),
                  argnums=(0, 1, 2))(x, k, b)
    xf, kf, bf = (jnp.asarray(t, jnp.float32) for t in (x, k, b))
    gr = jax.grad(tot(conv3x3_t_reference), argnums=(0, 1, 2))(xf, kf, bf)
    for a, r, name in zip(gk, gr, ("dx", "dw", "db")):
        assert a.dtype == jnp.bfloat16, name
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(r),
            rtol=0.05, atol=0.05, err_msg=name,
        )


@pytest.mark.slow  # tier-1 keeps test_pallas_conv.py::test_stats_variant
@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
def test_stats_variant(dt):
    """Same y; sum/sumsq equal the reductions of the ROUNDED output over
    (N, H, W) per channel (channel dim = axis 2 in this layout); grads
    still flow with stats cotangents zero by contract."""
    x, k, b = _data(dtype=dt)
    y, s, ss = conv3x3_t_stats(x, k, b, True)
    np.testing.assert_array_equal(np.asarray(y),
                                  np.asarray(conv3x3_t(x, k, b, True)))
    yf = np.asarray(y, np.float32).transpose(0, 1, 3, 2).reshape(
        -1, y.shape[2])
    assert s.shape == (y.shape[2], 1)
    np.testing.assert_allclose(np.asarray(s)[:, 0], yf.sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ss)[:, 0], (yf * yf).sum(0),
                               rtol=1e-5)

    def loss(x, k, b):
        y, s, ss = conv3x3_t_stats(x, k, b, True)
        return jnp.sum(y.astype(jnp.float32))

    gk = jax.grad(loss, argnums=(0, 1, 2))(x, k, b)
    gr = jax.grad(
        lambda x, k, b: jnp.sum(conv3x3_t(x, k, b, True).astype(jnp.float32)),
        argnums=(0, 1, 2),
    )(x, k, b)
    for a, r in zip(gk, gr):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(r))


def test_matches_nhwc_kernel_on_s2d_shapes():
    """Transposed kernel == NHWC kernel (modulo layout) on the exact
    s2d-scattered conv1 shapes ConvNetS2D uses, miniature image."""
    from tpu_sandbox.models.convnet_s2d import scatter_kernel, space_to_depth
    from tpu_sandbox.ops.pallas_conv import conv3x3

    rng = np.random.default_rng(3)
    img = jnp.asarray(rng.standard_normal((2, 40, 40)), jnp.float32)
    k5 = jnp.asarray(rng.standard_normal((5, 5, 1, 16)) * 0.2, jnp.float32)
    b = jnp.asarray(rng.standard_normal((16,)), jnp.float32)
    x = space_to_depth(img, 4)
    kg = scatter_kernel(k5, 4)
    bg = jnp.tile(b, 16)
    y_nhwc = conv3x3(x, kg, bg, True)
    y_t = conv3x3_t(x.transpose(0, 1, 3, 2), kg, bg, True)
    np.testing.assert_allclose(
        np.asarray(y_t.transpose(0, 1, 3, 2)), np.asarray(y_nhwc),
        rtol=1e-5, atol=1e-5,
    )


def test_wgrad_restage_variants_agree():
    """r05 wgrad restage: the explicit-gT native-dot variant and the
    Mosaic-auto lane-lane variant compute the SAME (dwT, db). Small
    interpret-mode shapes — equality is staging-independent math;
    production-geometry lowering of both variants is pinned in
    tests/test_mosaic_lowering.py."""
    from tpu_sandbox.ops.pallas_conv_t import conv3x3_t_wgrad

    rng = np.random.default_rng(7)
    for c, co in ((16, 32), (8, 16)):
        x = jnp.asarray(rng.standard_normal((2, 8, c, 32)), jnp.float32)
        g = jnp.asarray(rng.standard_normal((2, 8, co, 32)), jnp.float32)
        dw_gt, db_gt = conv3x3_t_wgrad(x, g, restage="gt")
        dw_auto, db_auto = conv3x3_t_wgrad(x, g, restage="auto")
        np.testing.assert_allclose(np.asarray(dw_gt), np.asarray(dw_auto),
                                   rtol=1e-6, atol=1e-4)
        np.testing.assert_allclose(np.asarray(db_gt), np.asarray(db_auto),
                                   rtol=1e-6, atol=1e-4)
