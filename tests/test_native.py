"""Native (C++) runtime tests: data loader parity with the Python loader,
prefetch correctness under threading, and the KV store's rendezvous
primitives (set/get/add/barrier) across threads and processes."""

import threading

import numpy as np
import pytest

pytest.importorskip("ctypes")

from tpu_sandbox.data import BatchLoader, DistributedSampler, synthetic_mnist
from tpu_sandbox.data.mnist import normalize

try:
    from tpu_sandbox.native.build import build_library

    build_library("dataloader")
    build_library("kvstore")
    HAVE_NATIVE = True
except Exception as e:  # no g++ in env
    HAVE_NATIVE = False
    NATIVE_ERR = e

needs_native = pytest.mark.skipif(not HAVE_NATIVE, reason="native build unavailable")


@needs_native
def test_native_loader_matches_python_loader():
    from tpu_sandbox.data.native_loader import NativeBatchLoader

    images, labels = synthetic_mnist(n=53, seed=0)
    py = BatchLoader(normalize(images), labels.astype("int32"), 8, shuffle=True, seed=3)
    nat = NativeBatchLoader(images, labels, 8, shuffle=True, seed=3, threads=3)
    py_batches, nat_batches = list(py), list(nat)
    assert len(py_batches) == len(nat_batches) == 7
    for (pi, pl), (ni, nl) in zip(py_batches, nat_batches):
        np.testing.assert_array_equal(pl, nl)
        np.testing.assert_allclose(pi, ni, atol=1e-7)
    assert nat_batches[-1][0].shape[0] == 53 % 8  # partial tail kept


@needs_native
def test_native_loader_epochs_reshuffle():
    from tpu_sandbox.data.native_loader import NativeBatchLoader

    images, labels = synthetic_mnist(n=64, seed=0)
    nat = NativeBatchLoader(images, labels, 16, shuffle=True, threads=2)
    first = np.concatenate([l for _, l in nat])
    again = np.concatenate([l for _, l in nat])
    np.testing.assert_array_equal(first, again)  # same epoch -> same order
    nat.set_epoch(1)
    third = np.concatenate([l for _, l in nat])
    assert not np.array_equal(first, third)


@needs_native
def test_native_loader_with_distributed_sampler():
    from tpu_sandbox.data.native_loader import NativeBatchLoader

    images, labels = synthetic_mnist(n=40, seed=0)
    loaders = [
        NativeBatchLoader(
            images, labels, 5,
            sampler=DistributedSampler(40, num_replicas=2, rank=r), threads=2,
        )
        for r in range(2)
    ]
    seen = [np.concatenate([l for _, l in ld]) for ld in loaders]
    assert len(seen[0]) == len(seen[1]) == 20


@needs_native
def test_native_loader_rejects_bad_input():
    from tpu_sandbox.data.native_loader import NativeBatchLoader

    images, labels = synthetic_mnist(n=8, seed=0)
    with pytest.raises(TypeError, match="uint8"):
        NativeBatchLoader(normalize(images), labels, 4)


@needs_native
def test_kvstore_set_get_add():
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    with KVServer() as srv:
        with KVClient(port=srv.port) as c:
            c.set("alpha", b"hello")
            assert c.get("alpha") == b"hello"
            assert c.add("counter", 5) == 5
            assert c.add("counter", 2) == 7
            c.set("alpha", "world")
            assert c.get("alpha") == b"world"
            c.delete("alpha")
            c.set("alpha", b"back")  # delete then set works
            assert c.get("alpha") == b"back"


@needs_native
def test_kvstore_blocking_get():
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    with KVServer() as srv:
        results = {}

        def waiter():
            with KVClient(port=srv.port) as c:
                results["value"] = c.get("later")

        t = threading.Thread(target=waiter)
        t.start()
        import time

        time.sleep(0.2)
        assert "value" not in results  # still blocked
        with KVClient(port=srv.port) as c:
            c.set("later", b"released")
        t.join(timeout=5)
        assert results["value"] == b"released"


@needs_native
def test_kvstore_barrier_across_threads():
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    with KVServer() as srv:
        n = 4
        passed = []
        lock = threading.Lock()

        def rank(i):
            with KVClient(port=srv.port) as c:
                c.barrier(n, key="b0")
                with lock:
                    passed.append(i)

        threads = [threading.Thread(target=rank, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert sorted(passed) == list(range(n))


@needs_native
def test_kvstore_multiprocess_rendezvous():
    """The reference smoke test's shape (test_init.py:112-117): N processes
    rendezvous through the store and all exit 0."""
    import multiprocessing as mp

    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    def worker(port, rank, world, q):
        try:
            with KVClient(port=port) as c:
                c.set(f"rank/{rank}", str(rank))
                c.barrier(world, key="join")
                got = sorted(int(c.get(f"rank/{r}")) for r in range(world))
                q.put((rank, got))
        except Exception as e:  # pragma: no cover
            q.put((rank, repr(e)))

    ctx = mp.get_context("fork")
    with KVServer() as srv:
        q = ctx.Queue()
        procs = [
            ctx.Process(target=worker, args=(srv.port, r, 3, q)) for r in range(3)
        ]
        for p in procs:
            p.start()
        results = [q.get(timeout=15) for _ in range(3)]
        for p in procs:
            p.join(timeout=5)
    assert all(got == [0, 1, 2] for _, got in results), results


@needs_native
def test_kvstore_token_auth(monkeypatch):
    """Shared-secret hello frame: a tokened server serves only connections
    that present the matching token first; a tokenless server ignores the
    whole mechanism (including a client that sends a hello anyway)."""
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    monkeypatch.delenv("TPU_SANDBOX_KV_TOKEN", raising=False)
    with KVServer(token="s3cret") as srv:
        with KVClient(port=srv.port, token="s3cret") as c:
            c.set("k", b"v")
            assert c.get("k") == b"v"
            with c.clone() as c2:  # clone re-authenticates
                assert c2.get("k") == b"v"
        with pytest.raises(ConnectionError, match="token"):
            KVClient(port=srv.port, token="wrong")
        # no token at all: the TCP connect succeeds but the first store op
        # is rejected before touching the map
        c3 = KVClient(port=srv.port)
        try:
            with pytest.raises(RuntimeError):
                c3.get("k")
        finally:
            c3.close()
    with KVServer() as srv:  # tokenless server: hello is a harmless no-op
        with KVClient(port=srv.port, token="ignored") as c:
            c.set("k", b"v")
            assert c.get("k") == b"v"


@needs_native
def test_kvstore_env_token_and_bind_all(monkeypatch):
    """TPU_SANDBOX_KV_TOKEN is the default token for BOTH ends (respawned
    workers inherit auth through the environment), and bind="0.0.0.0"
    accepts non-loopback-addressed connections."""
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    monkeypatch.setenv("TPU_SANDBOX_KV_TOKEN", "env-tok")
    with KVServer(bind="0.0.0.0") as srv:
        assert srv.token == "env-tok"
        with KVClient(port=srv.port) as c:  # token from env, no kwarg
            assert c.token == "env-tok"
            assert c.add("n", 1) == 1
        monkeypatch.delenv("TPU_SANDBOX_KV_TOKEN")
        with pytest.raises(ConnectionError, match="token"):
            KVClient(port=srv.port, token="not-it")
