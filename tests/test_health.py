"""Health plane tier-1 suite: the durable time-series ring, burn-rate /
threshold rules, the three seeded pathology repros (each with a clean
twin that must stay silent), exactly-once alerting through monitor
failover, and the alert→control loop closed end to end — the gateway
stops routing to a burning replica and resumes after recovery, the
autoscaler backs off its own oscillation, the scheduler stamps starved
jobs.

Everything runs on stub clocks where windows matter, so whole detection
windows pass in microseconds; the only real-time waits are short TTL
expiries (the recovery semantics ARE the TTL, so that part is real).
"""

import json
import os
import socket
import sys
import time

import pytest

from tpu_sandbox.gateway import wire
from tpu_sandbox.obs import tsdb
from tpu_sandbox.obs.health import (BurnRateRule, CascadeDetector,
                                    HealthMonitor, OscillationDetector,
                                    StarvationDetector, ThresholdRule,
                                    active_alerts, active_subjects, alerts,
                                    default_rules, k_active, k_alert_claim,
                                    k_alert_record, raise_alert)
from tpu_sandbox.obs.metrics import MetricsRegistry, get_registry, series_key
from tpu_sandbox.obs.record import Recorder
from tpu_sandbox.obs.tsdb import TimeSeriesFlusher
from tpu_sandbox.serve.cache import chain_digest

from tests.test_gateway import (BLOCK, _fake_report, _gateway,
                                kv_pair)  # noqa: F401 (fixture)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Clock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _flusher(kv, proc, clock, **kw):
    """A flusher on its OWN registry and a disabled recorder, so tests
    seed per-process series without touching the process-global state."""
    reg = MetricsRegistry()
    f = TimeSeriesFlusher(kv, proc, registry=reg, recorder=Recorder(None),
                          clock=clock, **kw)
    return f, reg


def _seed_burn(kv, proc, *, shed, done, clock=time.time):
    f, reg = _flusher(kv, proc, clock)
    reg.counter("engine.shed").inc(shed)
    reg.counter("engine.done").inc(done)
    f.flush()


# -- tsdb ring ----------------------------------------------------------------


def test_flusher_counter_deltas_accumulate_per_bucket(kv_pair):
    _, kv, _ = kv_pair
    clock = _Clock(1000.0)
    f, reg = _flusher(kv, "p0", clock)
    reg.counter("a.b").inc(5)
    assert f.flush() > 0
    rows = tsdb.read_series(kv, "a.b")
    assert [(r["kind"], r["v"], r["bucket"], r["proc"]) for r in rows] == \
        [("counter", 5, 1000, "p0")]
    # second flush in the SAME bucket: the bucket accumulates the delta
    reg.counter("a.b").inc(3)
    f.flush()
    rows = tsdb.read_series(kv, "a.b")
    assert [(r["v"], r["bucket"]) for r in rows] == [(8, 1000)]
    # next bucket starts from zero deltas
    clock.advance(1.0)
    reg.counter("a.b").inc(2)
    f.flush()
    rows = tsdb.read_series(kv, "a.b")
    assert [(r["v"], r["bucket"]) for r in rows] == [(8, 1000), (2, 1001)]
    assert tsdb.window_sum(rows, since_bucket=1000) == 10
    assert tsdb.window_sum(rows, since_bucket=1001) == 2
    assert tsdb.window_sum(rows, since_bucket=0, per_proc=True) == \
        {"p0": 10.0}


def test_flusher_gauges_histograms_and_label_series(kv_pair):
    _, kv, _ = kv_pair
    clock = _Clock(2000.0)
    f, reg = _flusher(kv, "p1", clock)
    reg.gauge("q.depth").set(3)
    h = reg.histogram("lat.s")
    for v in range(1, 101):
        h.observe(float(v))
    reg.counter("req.total", labels={"tenant": "a"}).inc(2)
    reg.counter("req.total", labels={"tenant": "b"}).inc(7)
    f.flush()
    assert tsdb.latest_value(tsdb.read_series(kv, "q.depth")) == 3
    # gauges are last-write-wins inside a bucket
    reg.gauge("q.depth").set(9)
    f.flush()
    assert tsdb.latest_value(tsdb.read_series(kv, "q.depth")) == 9
    # histogram digest: default field is p99
    p99 = tsdb.latest_value(tsdb.read_series(kv, "lat.s"))
    assert 90.0 <= p99 <= 100.0
    assert tsdb.latest_value(tsdb.read_series(kv, "lat.s"),
                             field="count") == 100
    # label variants are distinct series under one base name
    assert series_key("req.total", {"tenant": "a"}) == "req.total{tenant=a}"
    rows = tsdb.read_series(kv, "req.total")
    assert sorted(r["series"] for r in rows) == \
        ["req.total{tenant=a}", "req.total{tenant=b}"]
    assert tsdb.window_sum(rows, since_bucket=0) == 9
    # the flusher's synthetic recorder-health series ride along
    assert ("p1", "obs.recorder.dropped") in tsdb.list_series(kv)


def test_ring_wraps_bounded_and_ttl_expires(kv_pair):
    _, kv, _ = kv_pair
    clock = _Clock(100.0)
    f, reg = _flusher(kv, "ring", clock, retention_buckets=4, ds_factor=2)
    for _ in range(6):  # buckets 100..105 through a 4-slot ring
        reg.counter("w.x").inc()
        f.flush()
        clock.advance(1.0)
    rows = tsdb.read_series(kv, "w.x", proc="ring")
    # slots wrapped: only the last retention_buckets buckets survive, and
    # the absolute bucket in the payload is authoritative (no confusion
    # between bucket 100 and the bucket 104 that overwrote its slot)
    assert [r["bucket"] for r in rows] == [102, 103, 104, 105]
    keys = [k for k in kv.keys(tsdb.TS_PREFIX + "ring/") if "/w.x/" in k]
    assert len(keys) == 4
    # the coarse ring downsampled 2x: buckets 50, 51, 52 with summed deltas
    coarse = tsdb.read_series(kv, "w.x", proc="ring", coarse=True)
    assert [(r["bucket"], r["v"]) for r in coarse] == \
        [(50, 2), (51, 2), (52, 2)]


def test_ring_ttl_ages_out_dead_process_trails(kv_pair):
    _, kv, _ = kv_pair
    f, reg = _flusher(kv, "dead", time.time, bucket_s=0.05,
                      retention_buckets=2)
    reg.counter("t.x").inc()
    f.flush()
    assert tsdb.read_series(kv, "t.x", proc="dead")
    time.sleep(0.4)  # > retention_buckets * bucket_s
    assert tsdb.read_series(kv, "t.x", proc="dead") == []


def test_flusher_validates_inputs(kv_pair):
    _, kv, _ = kv_pair
    with pytest.raises(ValueError):
        TimeSeriesFlusher(kv, "a/b")
    with pytest.raises(ValueError):
        TimeSeriesFlusher(kv, "ok", ds_factor=1)


# -- rules --------------------------------------------------------------------


def test_burn_rate_rule_fires_on_both_windows_only(kv_pair):
    _, kv, _ = kv_pair
    clock = _Clock(5000.0)
    rule = BurnRateRule(name="shed_burn", bad="engine.shed",
                        good="engine.done", budget=0.05)
    # no traffic at all: no verdict, not a fire
    assert rule.evaluate(kv, 5000) == []
    _seed_burn(kv, "w0", shed=30, done=70, clock=clock)  # rate 0.3 > 0.2
    fired = rule.evaluate(kv, 5000)
    assert [s for s, _ in fired] == ["fleet"]
    assert fired[0][1]["short_rate"] == pytest.approx(0.3)
    # healthy traffic: under 4x budget, silent
    kv.delete_prefix(tsdb.TS_PREFIX)
    _seed_burn(kv, "w0", shed=1, done=99, clock=clock)
    assert rule.evaluate(kv, 5000) == []


def test_burn_rate_rule_per_proc_isolates_the_burning_replica(kv_pair):
    _, kv, _ = kv_pair
    clock = _Clock(5000.0)
    _seed_burn(kv, "good", shed=0, done=100, clock=clock)
    _seed_burn(kv, "bad", shed=50, done=50, clock=clock)
    rule = BurnRateRule(name="replica_burn", bad="engine.shed",
                        good="engine.done", budget=0.05, per_proc=True)
    fired = rule.evaluate(kv, 5000)
    assert [s for s, _ in fired] == ["bad"]


def test_threshold_rule_gauge_and_histogram_field(kv_pair):
    _, kv, _ = kv_pair
    clock = _Clock(3000.0)
    f, reg = _flusher(kv, "p0", clock)
    reg.gauge("serve.goodput").set(12.0)
    h = reg.histogram("engine.ttft")
    for v in (0.1, 0.2, 0.9):
        h.observe(v)
    f.flush()
    below = ThresholdRule(name="goodput_floor", series="serve.goodput",
                          threshold=20.0, op="<")
    fired = below.evaluate(kv, 3000)
    assert fired and fired[0][0] == "fleet" and fired[0][1]["value"] == 12.0
    assert ThresholdRule(name="x", series="serve.goodput",
                         threshold=5.0, op="<").evaluate(kv, 3000) == []
    ttft = ThresholdRule(name="ttft_slo", series="engine.ttft",
                         threshold=0.5, op=">", field="p99")
    assert ttft.evaluate(kv, 3000)
    # the stock rule set alerts on recorder drops: the flusher publishes
    # the synthetic obs.recorder.dropped gauge from recorder.stats()
    drops = [r for r in default_rules() if r.name == "recorder_drops"][0]
    assert drops.evaluate(kv, 3000) == []  # healthy recorder: 0 drops

    class _DroppingRec:
        enabled = False

        def stats(self):
            return {"events": 10, "dropped": 4}

    f2 = TimeSeriesFlusher(kv, "p0", registry=MetricsRegistry(),
                           recorder=_DroppingRec(), clock=clock)
    f2.flush()
    fired = drops.evaluate(kv, 3000)
    assert [s for s, _ in fired] == ["p0"]
    assert fired[0][1]["value"] == 4.0


# -- alert protocol: exactly-once through failover ----------------------------


def test_raise_alert_claims_exactly_once_per_window(kv_pair):
    _, kv, _ = kv_pair
    body = {"rule": "r", "subject": "s", "window_idx": 7, "wall": 1.0}
    assert raise_alert(kv, "r", "s", 7, body, active_ttl=30.0) is True
    # a second monitor evaluating the same window: record is idempotent,
    # claim is lost, active flag refreshed — no double notification
    assert raise_alert(kv, "r", "s", 7, body, active_ttl=30.0) is False
    assert json.loads(kv.get(k_alert_record("r", "s", 7))) == body
    assert active_subjects(kv, "r") == {"s"}
    # a new window is a new claim
    assert raise_alert(kv, "r", "s", 8, dict(body, window_idx=8),
                       active_ttl=30.0) is True
    assert len(alerts(kv, rule="r")) == 2


def test_monitor_killed_mid_evaluation_never_double_fires(kv_pair):
    _, kv, _ = kv_pair
    body = {"rule": "r", "subject": "s", "window_idx": 9, "wall": 2.0}
    # monitor A dies between the record write and the claim: replay its
    # first step only
    kv.set(k_alert_record("r", "s", 9), json.dumps(body, sort_keys=True))
    # successor B evaluates the same window and completes the protocol —
    # it wins the claim (A never got there), so the notification happens
    # exactly once
    assert raise_alert(kv, "r", "s", 9, body, active_ttl=30.0) is True
    # and a replay of A after resurrection cannot fire again
    assert raise_alert(kv, "r", "s", 9, body, active_ttl=30.0) is False
    assert kv.get(k_alert_claim("r", "s", 9)) == b"2"
    assert len(alerts(kv, rule="r")) == 1


def test_monitor_leader_election_onset_refresh_recovery(kv_pair):
    _, kv, _ = kv_pair
    clock = _Clock(7000.0)
    f, reg = _flusher(kv, "p0", clock)
    reg.gauge("q.depth").set(10.0)
    f.flush()
    rule = ThresholdRule(name="q_high", series="q.depth", threshold=5.0)

    def mon(member):
        # active TTL = 2 windows * 0.5 s = 1 s of real time: long enough
        # that back-to-back steps land inside it, short enough to test
        # recovery-by-expiry below
        return HealthMonitor(kv, member, window_s=0.5, bucket_s=1.0,
                             rules=[rule], detectors=[], active_windows=2.0,
                             clock=clock)

    m1, m2 = mon("h0"), mon("h1")
    claimed = m1.step()
    assert [b["rule"] for b in claimed] == ["q_high"]
    assert claimed[0]["subject"] == "fleet"
    # the follower is not evaluating at all
    assert m2.step() is None
    # while the condition holds, the leader refreshes the active flag but
    # raises no new record (onset vs refresh)
    assert m1.step() == []
    assert len(alerts(kv, rule="q_high")) == 1
    assert active_subjects(kv, "q_high") == {"fleet"}
    # failover: the successor leads and keeps refreshing without re-firing
    m1.resign()
    assert m2.step() == []
    assert len(alerts(kv, rule="q_high")) == 1
    # recovery: condition clears, the active flag TTLs out (0.1 s)
    kv.delete_prefix(tsdb.TS_PREFIX)
    deadline = time.monotonic() + 5.0
    while active_subjects(kv, "q_high") and time.monotonic() < deadline:
        time.sleep(0.02)
    assert active_subjects(kv, "q_high") == set()
    assert m2.step() == []  # clear condition: nothing fires
    # relapse in a LATER window: a fresh onset record
    reg.gauge("q.depth").set(11.0)
    f.flush()
    clock.advance(1.0)
    claimed = m2.step()
    assert len(claimed) == 1
    assert len(alerts(kv, rule="q_high")) == 2
    # the claimed notification bumped the health.alerts counter
    snap = get_registry().snapshot()["counters"]
    assert snap.get('health.alerts{rule=q_high}', 0) >= 2


# -- seeded pathologies + clean twins -----------------------------------------


def _seed_autoscale_events(kv, actions, *, reason="queue_depth"):
    from tpu_sandbox.serve.autoscale import K_EVENT_TAIL, k_event

    tail = int(kv.try_get(K_EVENT_TAIL) or b"0")
    for a in actions:
        kv.set(k_event(tail), json.dumps(
            {"action": a, "reason": reason, "wall": 0.0}))
        tail += 1
    kv.set(K_EVENT_TAIL, str(tail))


def test_oscillation_detector_fires_on_flapping(kv_pair):
    _, kv, _ = kv_pair
    det = OscillationDetector(window_evals=8, flip_threshold=3)
    _seed_autoscale_events(
        kv, ["scale_up", "scale_down", "scale_up", "scale_down"])
    fired = det.observe(kv)
    assert [s for s, _ in fired] == ["fleet"]
    assert fired[0][1]["flips"] == 3
    # the window slides: with no new events the flips age out
    for _ in range(10):
        fired = det.observe(kv)
    assert fired == []


def test_oscillation_clean_twins_stay_silent(kv_pair):
    _, kv, _ = kv_pair
    # monotonic growth is not oscillation
    det = OscillationDetector(window_evals=8, flip_threshold=3)
    _seed_autoscale_events(kv, ["scale_up"] * 5)
    assert det.observe(kv) == []
    # bootstrap floor-repair events never count, however many alternate
    kv.delete_prefix("serve/autoscale/")
    det2 = OscillationDetector(window_evals=8, flip_threshold=3)
    _seed_autoscale_events(
        kv, ["scale_up", "scale_down", "scale_up", "scale_down"],
        reason="min_replicas")
    assert det2.observe(kv) == []


def _seed_tenant(kv, tenant, *, vtime, queued):
    from tpu_sandbox.runtime.scheduler import (K_QUEUED_PREFIX,
                                               K_VTIME_PREFIX)

    kv.set(f"{K_VTIME_PREFIX}{tenant}", repr(float(vtime)))
    kv.set(f"{K_QUEUED_PREFIX}{tenant}", str(int(queued)))


def test_starvation_detector_fires_on_share_abuse(kv_pair):
    _, kv, _ = kv_pair
    det = StarvationDetector(ratio=5.0, consecutive=2)
    # tenant "hog" (10:1 share) advances; "mouse" has queued work but its
    # vtime is frozen — the fair-share invariant says both should move
    _seed_tenant(kv, "hog", vtime=0.0, queued=0)
    _seed_tenant(kv, "mouse", vtime=0.0, queued=2)
    assert det.observe(kv) == []  # first observation only seeds deltas
    _seed_tenant(kv, "hog", vtime=10.0, queued=0)
    assert det.observe(kv) == []  # streak 1 of 2: admission churn immunity
    _seed_tenant(kv, "hog", vtime=20.0, queued=0)
    fired = det.observe(kv)
    assert [s for s, _ in fired] == ["mouse"]
    assert fired[0][1]["queued"] == 2


def test_starvation_clean_twin_both_tenants_advance(kv_pair):
    _, kv, _ = kv_pair
    det = StarvationDetector(ratio=5.0, consecutive=2)
    _seed_tenant(kv, "a", vtime=0.0, queued=1)
    _seed_tenant(kv, "b", vtime=0.0, queued=1)
    det.observe(kv)
    for step in (10.0, 20.0, 30.0):
        # both advance at comparable rates (well inside the 5x ratio)
        _seed_tenant(kv, "a", vtime=step, queued=1)
        _seed_tenant(kv, "b", vtime=step * 0.5, queued=1)
        assert det.observe(kv) == []


def test_cascade_detector_fires_on_preempt_cycles(kv_pair):
    from tpu_sandbox.runtime.scheduler import K_PREEMPTS_PREFIX

    _, kv, _ = kv_pair
    det = CascadeDetector(cycles=3, window_evals=8)
    kv.add(f"{K_PREEMPTS_PREFIX}victim")
    assert det.observe(kv) == []  # one preemption is business as usual
    kv.add(f"{K_PREEMPTS_PREFIX}victim")
    assert det.observe(kv) == []
    kv.add(f"{K_PREEMPTS_PREFIX}victim")
    fired = det.observe(kv)
    assert [s for s, _ in fired] == ["victim"]
    assert fired[0][1]["preemptions"] == 3
    # clean twin: a job preempted once long ago never re-fires; the
    # window slides past the cycles
    for _ in range(10):
        fired = det.observe(kv)
    assert fired == []


# -- the loop closed: alerts drive control ------------------------------------


def test_gateway_excludes_burning_replica_until_recovery(kv_pair):
    _, kv, _ = kv_pair
    prompt = list(range(1, 13))
    chain = chain_digest(prompt, BLOCK)
    # "burned" advertises the deepest prefix residency: absent the health
    # plane, routing would always pick it
    _fake_report(kv, "burned", digest=chain)
    _fake_report(kv, "healthy", digest=chain[:1])
    _seed_burn(kv, "burned", shed=30, done=10)
    mon = HealthMonitor(
        kv, "h0", window_s=0.25, active_windows=2.0,
        rules=[BurnRateRule(name="replica_burn", bad="engine.shed",
                            good="engine.done", budget=0.05,
                            per_proc=True)],
        detectors=[])
    claimed = mon.step()
    assert [b["subject"] for b in claimed] == ["burned"]
    assert active_subjects(kv, "replica_burn") == {"burned"}

    def _route(gw, rid):
        s = socket.create_connection(("127.0.0.1", gw.port), timeout=5)
        try:
            wire.send_frame(s, wire.OP_SUBMIT, {
                "rid": rid, "prompt": prompt, "max_new_tokens": 2})
            status, resp = wire.recv_response(s)
            assert status == wire.ST_OK and resp["admitted"], resp
            return resp["replica"]
        finally:
            s.close()

    with _gateway(kv) as gw:
        # burn active: the deepest replica is OFF the table
        assert _route(gw, "r0") == "healthy"
        # recovery: the monitor stops refreshing (condition owner died /
        # condition cleared) and the active flag TTLs out (0.5 s)
        deadline = time.monotonic() + 10.0
        while active_subjects(kv, "replica_burn") \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert active_subjects(kv, "replica_burn") == set()
        time.sleep(0.05)  # next refresh re-reads health state
        assert _route(gw, "r1") == "burned"


def test_autoscaler_backs_off_on_its_own_oscillation(kv_pair):
    from tpu_sandbox.serve.autoscale import AutoscaleConfig, ReplicaAutoscaler

    _, kv, _ = kv_pair
    cfg = AutoscaleConfig(min_replicas=0, max_replicas=4,
                          hysteresis_ticks=1, cooldown_s=0.0)
    asc = ReplicaAutoscaler(kv, ["true"], cfg=cfg)
    _fake_report(kv, "r0", queue_depth=10)  # loud scale-up signal
    kv.set_ttl(k_active("autoscale_oscillation", "fleet"), b"{}", 0.4)
    before = get_registry().snapshot()["counters"].get(
        "autoscale.backoff", 0)
    # the health plane says we're flapping: load-driven scaling freezes
    assert asc.tick() is None
    assert asc.tick() is None
    after = get_registry().snapshot()["counters"]["autoscale.backoff"]
    assert after == before + 2
    # alert expires -> the same signal scales up again
    deadline = time.monotonic() + 5.0
    while active_subjects(kv, "autoscale_oscillation") \
            and time.monotonic() < deadline:
        time.sleep(0.02)
    event = asc.tick()
    assert event is not None and event["action"] == "scale_up"


def test_scheduler_stamps_starved_jobs_once(kv_pair):
    from tpu_sandbox.runtime.scheduler import (ClusterScheduler, JobSpec,
                                               job_events, submit_job)

    _, kv, _ = kv_pair
    with ClusterScheduler(1, kv_port=kv.port, poll=0.02,
                          verbose=False) as sched:
        # a 2-host gang on a 1-slot pool: queued forever, zero agents
        submit_job(kv, JobSpec(job_id="wide", hosts=2, world_size=2,
                               agent_argv=["true"], tenant="mouse"))
        sched._tick()
        # queue shape is published durably for the starvation detector
        assert kv.try_get("sched/queued/mouse") == b"1"
        assert "starved" not in job_events(kv, "wide")
        # the health plane flags the tenant: the next tick surfaces it in
        # the job's own durable event stream
        kv.set_ttl(k_active("tenant_starvation", "mouse"), b"{}", 5.0)
        sched._tick()
        stamp = job_events(kv, "wide")["starved"]
        # once: later ticks with the alert still active do not re-stamp
        time.sleep(0.01)
        sched._tick()
        assert job_events(kv, "wide")["starved"] == stamp


# -- fleetop console ----------------------------------------------------------


def test_fleetop_renders_fleet_health(kv_pair):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import fleetop

    _, kv, _ = kv_pair
    assert "no time series" in fleetop.render(kv)  # empty store renders
    clock = _Clock(time.time())
    f, reg = _flusher(kv, "sched", clock)
    reg.gauge("sched.queue.depth").set(4)
    f.flush()
    _fake_report(kv, "w0", queue_depth=2)
    _seed_burn(kv, "w0", shed=30, done=10)
    raise_alert(kv, "replica_burn", "w0", 1,
                {"rule": "replica_burn", "subject": "w0",
                 "window_idx": 1, "wall": time.time()}, active_ttl=30.0)
    out = fleetop.render(kv, now=time.time())
    assert "sched.queue.depth" in out
    assert "w0" in out and "EXCLUDED" in out
    assert "active alerts (1)" in out and "replica_burn" in out
    assert "recent alert records" in out
    assert "postmortem" in out
