"""utils/profiling.py coverage: the trace() wrapper (including the
newer-jax ``start_trace`` signature fallback), region annotation, the
fetch-synced host_sync primitive, StepTimer, and the differential
per-step measurement — all on CPU with stubbed profilers where the real
one would write trace directories."""

import math
import time

import jax.numpy as jnp
import pytest

from tpu_sandbox.utils import profiling


class _StubProfiler:
    """Records start/stop calls; optionally rejects the tracer-options
    kwarg the way newer jax releases do."""

    def __init__(self, accepts_options: bool):
        self.accepts_options = accepts_options
        self.calls = []

    def start_trace(self, logdir, **kwargs):
        if kwargs and not self.accepts_options:
            raise TypeError(
                "start_trace() got an unexpected keyword argument "
                f"{next(iter(kwargs))!r}")
        self.calls.append(("start", logdir, dict(kwargs)))

    def stop_trace(self):
        self.calls.append(("stop",))


def test_trace_passes_tracer_options_when_supported(monkeypatch, tmp_path):
    stub = _StubProfiler(accepts_options=True)
    monkeypatch.setattr(profiling.jax, "profiler", stub)
    with profiling.trace(str(tmp_path), host_tracer_level=3):
        pass
    assert stub.calls == [
        ("start", str(tmp_path), {"host_tracer_level": 3}),
        ("stop",),
    ]


def test_trace_falls_back_when_start_trace_rejects_options(
        monkeypatch, tmp_path):
    # newer jax moved tracer options off start_trace: the first attempt
    # raises TypeError and trace() must retry bare, not propagate
    stub = _StubProfiler(accepts_options=False)
    monkeypatch.setattr(profiling.jax, "profiler", stub)
    with profiling.trace(str(tmp_path)):
        pass
    assert stub.calls == [("start", str(tmp_path), {}), ("stop",)]


def test_trace_stops_profiler_on_body_exception(monkeypatch, tmp_path):
    stub = _StubProfiler(accepts_options=True)
    monkeypatch.setattr(profiling.jax, "profiler", stub)
    with pytest.raises(RuntimeError, match="boom"):
        with profiling.trace(str(tmp_path)):
            raise RuntimeError("boom")
    assert stub.calls[-1] == ("stop",)


def test_annotate_names_a_region():
    # the real TraceAnnotation is a cheap no-op off-profiler; the context
    # must simply nest without error
    with profiling.annotate("outer"):
        with profiling.annotate("inner"):
            pass


def test_host_sync_fetches_a_data_dependent_scalar():
    x = jnp.arange(8, dtype=jnp.float32) + 1.0
    assert profiling.host_sync(x) == 1.0
    assert profiling.host_sync(jnp.zeros((2, 3))) == 0.0


def test_step_timer_warmup_and_rates():
    t = profiling.StepTimer(warmup=1)
    t.start()
    for _ in range(3):
        time.sleep(0.002)
        t.tick(n_items=4)
    # warmup discards the first step: two measured
    assert len(t.step_times) == 2
    assert t.seconds_per_step >= 0.002
    assert t.items_per_second == pytest.approx(
        8 / sum(t.step_times))


def test_step_timer_tick_before_start_only_arms():
    t = profiling.StepTimer(warmup=0)
    t.tick(n_items=4)  # no start(): arms the clock, measures nothing
    assert t.step_times == []
    assert math.isnan(t.seconds_per_step)
    assert math.isnan(t.items_per_second)
    time.sleep(0.001)
    t.tick(n_items=4)
    assert len(t.step_times) == 1


def test_measure_per_step_cancels_fixed_costs():
    fixed, per_step = 0.004, 0.001

    def run_steps(k):
        time.sleep(fixed + per_step * k)
        return jnp.ones((1,))

    out = profiling.measure_per_step(run_steps, n=4)
    assert out["n"] == 4
    assert out["t_2n_sec"] > out["t_n_sec"]
    # the constant cost cancels: the estimate tracks per_step, not
    # fixed + per_step
    assert out["sec_per_step"] == pytest.approx(per_step, rel=0.75)
    assert "differential" in out["timing_method"]


def test_measure_per_step_repeated_publishes_spread():
    def run_steps(k):
        time.sleep(0.001 * k)
        return jnp.ones((1,))

    out = profiling.measure_per_step_repeated(run_steps, n=2, repeats=2)
    assert out["repeats"] == 2
    assert len(out["sec_per_step_samples"]) == 2
    assert out["sec_per_step"] > 0
    if out["spread_frac"] is not None:
        assert out["spread_frac"] >= 0
