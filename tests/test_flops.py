"""FLOP model / MFU accounting tests (utils/flops.py) — the bench's
plausibility cross-check must itself be correct, since it gates what
numbers get published (BASELINE.md 'the r01 anomaly, explained')."""

import pytest

from tpu_sandbox.utils.flops import (
    ConvNetFlops,
    conv2d_flops,
    convnet_flops,
    device_peak_tflops,
    mfu,
    transformer_flops,
)


def test_conv2d_flops_analytic():
    # 2 * H*W * C_out * k² * C_in
    assert conv2d_flops(10, 10, 3, 8, 5) == 2 * 100 * 8 * 25 * 3


def test_convnet_flops_at_3000_matches_verdict_analysis():
    """VERDICT r01 weak #1 derived conv1 ≈ 7.2, conv2 ≈ 57.6, fc ≈ 0.36
    GFLOP/img forward — the model must reproduce that analysis."""
    f = convnet_flops(3000)
    assert f.conv1 == pytest.approx(7.2e9)
    assert f.conv2 == pytest.approx(57.6e9)
    assert f.fc == pytest.approx(0.36e9)
    assert f.forward == pytest.approx(65.16e9)
    # training: 3x forward minus conv1's never-formed input gradient
    assert f.train == pytest.approx(3 * 65.16e9 - 7.2e9)


def test_convnet_flops_agrees_with_xla_cost_analysis():
    """The independent cross-check bench.py runs in production: XLA's own
    HLO FLOP count for one train step vs the analytic model (XLA also
    counts the resize/BN arithmetic, so it sits slightly above)."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpu_sandbox.models import ConvNet
    from tpu_sandbox.train import TrainState, make_train_step

    size, bs = 64, 2
    model = ConvNet()
    tx = optax.sgd(1e-4)
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, size, size, 1)), tx
    )
    step = make_train_step(model, tx, donate=False)
    lowered = jax.jit(step).lower(
        state, jnp.zeros((bs, size, size, 1)), jnp.zeros((bs,), jnp.int32)
    )
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    if not cost or "flops" not in cost:
        pytest.skip("backend exposes no cost analysis")
    model_flops = convnet_flops(size).train * bs
    ratio = float(cost["flops"]) / model_flops
    assert 0.95 < ratio < 1.25, (cost["flops"], model_flops)


def test_peak_table_and_mfu_verdicts():
    assert device_peak_tflops("TPU v5 lite") == 197.0
    assert device_peak_tflops("TPU v4") == 275.0
    assert device_peak_tflops("cpu") is None

    # a sane measurement: 1 TFLOP in 10 ms on a v5e -> 100 TFLOP/s, ~51%
    r = mfu(1e12, 0.010, "TPU v5 lite")
    assert r["achieved_tflops"] == pytest.approx(100.0)
    assert r["mfu"] == pytest.approx(100 / 197, rel=1e-3)
    assert r["plausible"]

    # the r01 failure mode: 2 PFLOP/s claimed on one v5e -> flagged
    r = mfu(1e12, 0.0005, "TPU v5 lite")
    assert r["mfu"] > 1 and not r["plausible"]

    # unknown chip: no peak, no verdict — but not declared implausible
    r = mfu(1e12, 0.010, "cpu")
    assert r["mfu"] is None and r["plausible"]

    # multi-chip peak scales
    r = mfu(1e12, 0.010, "TPU v5 lite", n_devices=4)
    assert r["peak_tflops_bf16"] == pytest.approx(4 * 197.0)


def test_transformer_flops_shape():
    f = transformer_flops(n_layers=2, d_model=64, d_ff=256, seq=128, vocab=100)
    per_layer = 2 * 4 * 64 * 64 + 2 * 2 * 64 * 256 + 2 * 2 * 128 * 64
    assert f["forward"] == pytest.approx(2 * per_layer + 2 * 64 * 100)
    assert f["train"] == pytest.approx(3 * f["forward"])


def test_convnet_flops_dataclass_is_frozen():
    f = convnet_flops(100)
    assert isinstance(f, ConvNetFlops)
    with pytest.raises(Exception):
        f.conv1 = 0.0


def test_s2d_custom_call_flops_counts_pallas_calls_only():
    """VERDICT r03 next-8: the composed FLOP cross-check counts Pallas
    custom calls by kernel class from optimized HLO and must IGNORE plain
    XLA gathers/scatters under the same module paths."""
    from tpu_sandbox.utils.flops import s2d_custom_call_flops

    hlo = "\n".join([
        '  %conv1.2 = bf16[1] custom-call(%a), metadata={op_name='
        '"jit(s)/jvp(M)/conv1/pallas_call"}',
        '  %conv2.4 = bf16[1] custom-call(%a), metadata={op_name='
        '"jit(s)/transpose(jvp(M))/conv2/pallas_call"}',
        '  %bn1.fused.3 = bf16[1] custom-call(%a), metadata={op_name='
        '"jit(s)/jvp(M)/M._tail/bn1.fused/pallas_call"}',
        # must NOT count: an XLA gather under the conv1 path
        '  %gather.8 = bf16[1] gather(%a), metadata={op_name='
        '"jit(s)/jvp(M)/conv1/gather"}',
        # must NOT count: a non-pallas custom call
        '  %custom-call.5 = bf16[1] custom-call(%a), metadata={op_name='
        '"jit(s)/jvp(jit(take_along_axis))/gather"}',
    ])
    base = 2.0 * 16 * 750 * 750
    # transposed plan: conv1 is the sparse-tap union-tile kernel (K=64)
    c = s2d_custom_call_flops(hlo, 16, 3000, plan="ConvNetS2DT")
    assert c["custom_calls_counted"] == 3
    assert c["unmatched_pallas_calls"] == 0
    assert c["per_class"]["conv1"] == base * 64 * 256
    assert c["per_class"]["conv2"] == base * 9 * 64 * 128
    assert c["per_class"]["bn1.fused"] == base * 256 * 64
    # NHWC s2d plan: conv1 is the scattered 3x3 (K=9*16)
    c2 = s2d_custom_call_flops(hlo, 16, 3000, plan="ConvNetS2D")
    assert c2["per_class"]["conv1"] == base * 9 * 16 * 256
    # ADVICE r04 medium: the EXECUTED kernel choice overrides the class
    # name — ConvNetS2DT running the scattered-3x3 conv1 (the sweep's
    # s2dt_scat_conv1 A/B row) must count K=9*16, not the sparse K=64
    c3 = s2d_custom_call_flops(hlo, 16, 3000, plan="ConvNetS2DT",
                               sparse_conv1=False)
    assert c3["per_class"]["conv1"] == base * 9 * 16 * 256


def test_model_runs_sparse_conv1_tracks_field_and_env(monkeypatch):
    """The cross-check keys on the executed conv1 kernel: the model's
    sparse_conv1 field AND the TPU_SANDBOX_NO_SPARSE_CONV1 kill switch
    (ADVICE r04 medium)."""
    from tpu_sandbox.models.convnet_s2d_t import ConvNetS2DT
    from tpu_sandbox.utils.flops import model_runs_sparse_conv1

    monkeypatch.delenv("TPU_SANDBOX_NO_SPARSE_CONV1", raising=False)
    assert model_runs_sparse_conv1(ConvNetS2DT())
    assert not model_runs_sparse_conv1(ConvNetS2DT(sparse_conv1=False))
    monkeypatch.setenv("TPU_SANDBOX_NO_SPARSE_CONV1", "1")
    assert not model_runs_sparse_conv1(ConvNetS2DT())

    class NotS2DT:
        sparse_conv1 = True

    monkeypatch.delenv("TPU_SANDBOX_NO_SPARSE_CONV1", raising=False)
    assert not model_runs_sparse_conv1(NotS2DT())
