"""Ring attention vs the single-device reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.ops.attention import causal_attention
from tpu_sandbox.parallel.ring_attention import make_ring_attention
from tpu_sandbox.runtime.mesh import make_mesh


def qkv(b=2, s=32, h=2, d=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape) for k in ks)


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh({"sp": 8})


def test_ring_matches_reference_causal(sp_mesh):
    q, k, v = qkv()
    ref = causal_attention(q, k, v, causal=True)
    ring = make_ring_attention(sp_mesh, "sp", causal=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), atol=1e-5)


def test_ring_matches_reference_noncausal(sp_mesh):
    q, k, v = qkv(seed=1)
    ref = causal_attention(q, k, v, causal=False)
    ring = make_ring_attention(sp_mesh, "sp", causal=False)(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), atol=1e-5)


def test_ring_output_stays_sharded(sp_mesh):
    q, k, v = qkv()
    out = make_ring_attention(sp_mesh, "sp")(q, k, v)
    assert len(out.addressable_shards) == 8
    assert out.addressable_shards[0].data.shape == (2, 4, 2, 8)


def test_ring_first_token_attends_only_itself(sp_mesh):
    """Causality across shard boundaries: token 0's output must equal v[0]
    regardless of later tokens."""
    q, k, v = qkv(seed=2)
    out = np.asarray(make_ring_attention(sp_mesh, "sp")(q, k, v))
    np.testing.assert_allclose(out[:, 0], np.asarray(v)[:, 0], atol=1e-5)

    # and perturbing the future must not change token 0 (nor any past token's view)
    v2 = v.at[:, 16:].set(99.0)
    out2 = np.asarray(make_ring_attention(sp_mesh, "sp")(q, k, v2))
    np.testing.assert_allclose(out2[:, :16], out[:, :16], atol=1e-5)


def test_ring_bf16_inputs(sp_mesh):
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv(seed=3))
    ref = causal_attention(q, k, v)
    ring = make_ring_attention(sp_mesh, "sp")(q, k, v)
    assert ring.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(ring, np.float32), np.asarray(ref, np.float32), atol=2e-2
    )


def test_ring_validates_axis(sp_mesh):
    with pytest.raises(ValueError, match="not in mesh"):
        make_ring_attention(sp_mesh, "nope")
