"""Ring attention vs the single-device reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.ops.attention import causal_attention
from tpu_sandbox.parallel.ring_attention import make_ring_attention
from tpu_sandbox.runtime.mesh import make_mesh


def qkv(b=2, s=32, h=2, d=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape) for k in ks)


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh({"sp": 8})


def test_ring_matches_reference_causal(sp_mesh):
    q, k, v = qkv()
    ref = causal_attention(q, k, v, causal=True)
    ring = make_ring_attention(sp_mesh, "sp", causal=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), atol=1e-5)


def test_ring_matches_reference_noncausal(sp_mesh):
    q, k, v = qkv(seed=1)
    ref = causal_attention(q, k, v, causal=False)
    ring = make_ring_attention(sp_mesh, "sp", causal=False)(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), atol=1e-5)


def test_ring_output_stays_sharded(sp_mesh):
    q, k, v = qkv()
    out = make_ring_attention(sp_mesh, "sp")(q, k, v)
    assert len(out.addressable_shards) == 8
    assert out.addressable_shards[0].data.shape == (2, 4, 2, 8)


def test_ring_first_token_attends_only_itself(sp_mesh):
    """Causality across shard boundaries: token 0's output must equal v[0]
    regardless of later tokens."""
    q, k, v = qkv(seed=2)
    out = np.asarray(make_ring_attention(sp_mesh, "sp")(q, k, v))
    np.testing.assert_allclose(out[:, 0], np.asarray(v)[:, 0], atol=1e-5)

    # and perturbing the future must not change token 0 (nor any past token's view)
    v2 = v.at[:, 16:].set(99.0)
    out2 = np.asarray(make_ring_attention(sp_mesh, "sp")(q, k, v2))
    np.testing.assert_allclose(out2[:, :16], out[:, :16], atol=1e-5)


def test_ring_bf16_inputs(sp_mesh):
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv(seed=3))
    ref = causal_attention(q, k, v)
    ring = make_ring_attention(sp_mesh, "sp")(q, k, v)
    assert ring.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(ring, np.float32), np.asarray(ref, np.float32), atol=2e-2
    )


def test_ring_validates_axis(sp_mesh):
    with pytest.raises(ValueError, match="not in mesh"):
        make_ring_attention(sp_mesh, "nope")


# --- Ulysses (all-to-all) sequence parallelism ---------------------------

def test_ulysses_matches_reference_and_ring(sp_mesh):
    from tpu_sandbox.parallel.ulysses import make_ulysses_attention

    q, k, v = qkv(h=8, seed=2)  # H == 8 ranks -> 1 head per rank
    ref = causal_attention(q, k, v, causal=True)
    uly = make_ulysses_attention(sp_mesh, "sp", causal=True)(q, k, v)
    ring = make_ring_attention(sp_mesh, "sp", causal=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ring), atol=1e-5)


def test_ulysses_noncausal(sp_mesh):
    from tpu_sandbox.parallel.ulysses import make_ulysses_attention

    q, k, v = qkv(h=16, seed=3)  # 2 heads per rank
    ref = causal_attention(q, k, v, causal=False)
    uly = make_ulysses_attention(sp_mesh, "sp", causal=False)(q, k, v)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ref), atol=1e-5)


def test_ulysses_rejects_indivisible_heads(sp_mesh):
    from tpu_sandbox.parallel.ulysses import make_ulysses_attention

    q, k, v = qkv(h=2)  # 2 heads over 8 ranks
    with pytest.raises(ValueError, match="heads % ranks"):
        make_ulysses_attention(sp_mesh, "sp")(q, k, v)


def test_seq_parallel_ulysses_trains_like_ring():
    import optax

    from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
    from tpu_sandbox.parallel import SeqParallel

    cfg = TransformerConfig(vocab_size=16, d_model=16, n_heads=4, n_layers=2,
                            d_ff=32, max_len=32)
    mesh = make_mesh({"data": 2, "sp": 4})
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 16, size=(4, 32)).astype(np.int32)
    targets = ((tokens + 1) % 16).astype(np.int32)

    losses = {}
    for attn in ("ring", "ulysses"):
        eng = SeqParallel(lambda a: TransformerLM(cfg, attention_fn=a),
                          optax.sgd(1e-2), mesh, attn=attn, donate=False)
        state = eng.shard_state(eng.init_state(jax.random.key(0),
                                               jnp.asarray(tokens)))
        _, loss = eng.train_step(state, *eng.shard_batch(tokens, targets))
        losses[attn] = float(np.asarray(loss))
    np.testing.assert_allclose(losses["ring"], losses["ulysses"], rtol=1e-5)
