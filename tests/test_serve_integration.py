"""End-to-end serving gang (CPU, 2 replicas under real HostAgents): kill
one replica's agent mid-load and lose nothing.

The launcher plays autoscaler: AgentLauncher owns the KV store and spawns
2 HostAgent processes, each running one replica rank
(``python -m tpu_sandbox.serve.replica``). The test is the producer — it
enqueues the whole request load up front, waits for the gang to get
partway through, then SIGKILLs agent 1 via the fault mailbox. That
exercises every loss path at once:

- agent 1 dies uncleanly; pdeathsig takes its replica down with claimed
  requests in flight (leases expire, nobody says goodbye);
- the launcher replaces the agent; the replacement reports its lost
  ranks, the leader tears the generation down;
- the surviving replica drains on SIGTERM (requeues its in-flight work,
  exits preempted), and generation 2 relaunches both replicas;
- gen-2 scavenge requeues the killed replica's orphaned claims.

Zero loss means: every request has a result, and every result is
token-identical to the unfaulted greedy reference (greedy argmax over
bitwise-deterministic decode steps — see serve/decode.py — makes replay
exact, so "identical to a run with no fault" is a literal equality).

Real subprocesses + four cold jax compiles (2 replicas x 2 generations):
slow-marked, out of tier-1. The replica protocol runs fast and in-process
in test_serve.py.
"""

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent

N_REQUESTS = 80
MAX_CTX = 32

# Must mirror replica._build_engine's defaults (param_seed included) so the
# in-test reference uses bitwise-identical params and geometry.
SERVE_CFG = {
    "cache": {"num_blocks": 24, "block_size": 4, "max_blocks_per_seq": 8},
    "max_batch": 3,
    "buckets": [8, 16],
    "param_seed": 0,
    "lease_ttl": 1.0,
    "timeout": 240.0,
}


def _agent_main(argv):
    """One host agent whose single rank is a serve replica (the process
    the AgentLauncher spawns when this file is run as a script)."""
    import argparse

    from tpu_sandbox.runtime.host_agent import AgentConfig, HostAgent

    p = argparse.ArgumentParser()
    p.add_argument("--agent-id", type=int, required=True)
    p.add_argument("--agents", type=int, required=True)
    p.add_argument("--kv-port", type=int, required=True)
    p.add_argument("--config", required=True)
    args = p.parse_args(argv)

    cfg = AgentConfig(
        agent_id=args.agent_id, num_agents=args.agents,
        world_size=args.agents, kv_port=args.kv_port,
        lease_ttl=2.0, agent_timeout=4.0, term_timeout=10.0,
        backoff=0.1,
    )

    def rank_cmd(gen, rank, coord_port):
        return [sys.executable, "-m", "tpu_sandbox.serve.replica",
                "--config", args.config,
                "--tag", f"replica-r{rank}-g{gen}"]

    return HostAgent(cfg, rank_cmd).run()


def _requests(rng, n):
    out = []
    for i in range(n):
        prompt = [int(t) for t in
                  rng.integers(1, 64, size=int(rng.integers(4, 13)))]
        out.append((f"r{i}", prompt, int(rng.integers(8, 21))))
    return out


def _greedy_reference(reqs):
    """Unfaulted outputs via the padded one-shot forward — one compiled
    shape, bitwise-identical logits to the replicas' decode path."""
    import jax
    import jax.numpy as jnp

    from tpu_sandbox.models.transformer import (TransformerConfig,
                                                TransformerLM)

    mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                             n_layers=2, d_ff=64, max_len=128,
                             dtype=jnp.float32)
    model = TransformerLM(mcfg)
    params = model.init(jax.random.key(SERVE_CFG["param_seed"]),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    fwd = jax.jit(lambda t: model.apply({"params": params}, t))
    want = {}
    for rid, prompt, max_new in reqs:
        toks = list(prompt)
        out = []
        for _ in range(max_new):
            padded = np.zeros((1, MAX_CTX), np.int32)
            padded[0, :len(toks)] = toks
            t = int(np.asarray(fwd(jnp.asarray(padded)))[0, len(toks) - 1]
                    .argmax())
            out.append(t)
            toks.append(t)
        want[rid] = out
    return want


def test_replica_gang_survives_agent_kill_with_zero_loss(tmp_path):
    from tpu_sandbox.runtime.faults import agent_cmd_key
    from tpu_sandbox.runtime.host_agent import K_JOB_DONE, AgentLauncher
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve import replica as R

    rng = np.random.default_rng(0)
    reqs = _requests(rng, N_REQUESTS)

    server = KVServer()
    kv = KVClient(port=server.port)
    cfg_json = json.dumps(SERVE_CFG)

    def agent_cmd(aid, kv_port):
        return [sys.executable, str(Path(__file__).resolve()),
                "--serve-agent", "--agent-id", str(aid),
                "--agents", "2", "--kv-port", str(kv_port),
                "--config", cfg_json]

    trace_dir = tmp_path / "trace"
    launcher = AgentLauncher(
        2, agent_cmd, kv_server=server,
        extra_env={
            "JAX_PLATFORMS": "cpu",
            # conftest flips this in the test process; the replicas must
            # draw params from the same threefry stream or the reference
            # and the gang disagree from token 0
            "JAX_THREEFRY_PARTITIONABLE": "1",
            # flight recorder on in every agent/replica process: the
            # postmortem below reconstructs the incident from these logs
            "TPU_SANDBOX_TRACE_DIR": str(trace_dir),
            "PYTHONPATH": str(REPO) + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        })
    rc = []
    thread = threading.Thread(target=lambda: rc.append(launcher.run()),
                              name="serve-launcher")
    try:
        # load first, gang second: the queue is durable, replicas find it
        for rid, prompt, max_new in reqs:
            R.submit_request(kv, rid, prompt, max_new)
        R.announce_total(kv, N_REQUESTS)

        thread.start()

        # wait for the gang to be demonstrably mid-load: some results
        # published, most of the work still outstanding
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if len(kv.keys("serve/result/")) >= 3:
                break
            time.sleep(0.02)
        n_at_kill = len(kv.keys("serve/result/"))
        assert 0 < n_at_kill < N_REQUESTS, \
            f"no mid-load window: {n_at_kill}/{N_REQUESTS} at kill time"
        kv.set(agent_cmd_key(1), json.dumps({"action": "kill_agent"}))

        while launcher.respawns == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert launcher.respawns >= 1, "agent 1 was never replaced"

        thread.join(timeout=420)
        assert not thread.is_alive(), "launcher never saw a job verdict"
        assert rc and rc[0] == 0, f"job verdict not ok: rc={rc}"

        # zero loss: every request answered, every answer bitwise equal to
        # the unfaulted reference
        assert R.results_done(kv)
        want = _greedy_reference(reqs)
        for rid, _, _ in reqs:
            got = json.loads(kv.get(R.k_result(rid)))
            assert got["tokens"] == want[rid], rid
        # and the recovery actually ran through the requeue machinery:
        # drain and/or scavenge append fresh queue entries past the
        # producer's original N
        tail = int(kv.get(R.K_TAIL))
        assert tail > N_REQUESTS, \
            f"no requeues observed (tail {tail} == {N_REQUESTS})"

        # postmortem receipt: tracecat over the durable recorder logs
        # reconstructs the incident in causal order — the fault firing,
        # the dead claimant's lease expiring, the scavenger's requeue.
        # Instants are flushed before the SIGKILL executes, so the kill
        # record survives the process that wrote it.
        def tracecat(*args):
            proc = subprocess.run(
                [sys.executable, str(REPO / "tools" / "tracecat.py"),
                 str(trace_dir), *args],
                capture_output=True, text=True, timeout=120)
            assert proc.returncode == 0, proc.stderr
            return proc.stdout
        timeline = tracecat("--last", "600s")
        i_kill = timeline.index("fault:kill_agent")
        i_expire = timeline.index("lease:expired")
        i_requeue = timeline.index("scavenge:requeue")
        assert i_kill < i_expire < i_requeue, timeline
        # the exact incident-response invocation works too: the window is
        # measured back from the LAST record, so it always has content
        assert tracecat("--last", "10s").strip()
    finally:
        if thread.is_alive():
            # unwedge the launcher so teardown can't hang the suite
            kv.set(K_JOB_DONE, json.dumps(
                {"ok": False, "reason": "test teardown"}))
            thread.join(timeout=60)
        kv.close()
        server.stop()


def test_overload_plus_agent_kill_yields_exactly_one_verdict_each(tmp_path):
    """Chaos + SLO accounting: an overloaded gang (deadline'd cohorts
    queued behind cold compiles) loses an agent mid-load, and still every
    submitted request terminates with EXACTLY one verdict — an ok result
    or an explicit SHED — with no ok published materially past its
    deadline and no corruption in anything that did complete.

    Cohorts: A has no deadline (must all complete, bitwise-reference);
    B's deadline leaves room to finish unless the kill/relaunch eats it
    (either verdict is legal); C's deadline is tighter than the first
    cold compile, so C guarantees the shed path runs under chaos."""
    from tpu_sandbox.runtime.faults import agent_cmd_key
    from tpu_sandbox.runtime.host_agent import K_JOB_DONE, AgentLauncher
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve import replica as R

    rng = np.random.default_rng(1)
    reqs = _requests(rng, 60)
    cohort = {rid: ("A", "B", "C")[i % 3] for i, (rid, _, _) in
              enumerate(reqs)}

    server = KVServer()
    kv = KVClient(port=server.port)
    cfg_json = json.dumps(SERVE_CFG)

    def agent_cmd(aid, kv_port):
        return [sys.executable, str(Path(__file__).resolve()),
                "--serve-agent", "--agent-id", str(aid),
                "--agents", "2", "--kv-port", str(kv_port),
                "--config", cfg_json]

    launcher = AgentLauncher(
        2, agent_cmd, kv_server=server,
        extra_env={
            "JAX_PLATFORMS": "cpu",
            "JAX_THREEFRY_PARTITIONABLE": "1",
            "PYTHONPATH": str(REPO) + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        })
    rc = []
    thread = threading.Thread(target=lambda: rc.append(launcher.run()),
                              name="chaos-launcher")
    try:
        t0 = time.time()
        deadlines = {}
        for rid, prompt, max_new in reqs:
            dl = {"A": None, "B": t0 + 25.0, "C": t0 + 2.5}[cohort[rid]]
            deadlines[rid] = dl
            R.submit_request(kv, rid, prompt, max_new, deadline_unix=dl)
        R.announce_total(kv, len(reqs))

        thread.start()

        # monitor the verdict stream: first-seen wall time per rid, and
        # the kill once the gang is demonstrably mid-load
        first_seen = {}
        killed = False
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            for key in kv.keys("serve/result/"):
                rid = key[len("serve/result/"):]
                first_seen.setdefault(rid, time.time())
            if not killed and len(first_seen) >= 3:
                kv.set(agent_cmd_key(1),
                       json.dumps({"action": "kill_agent"}))
                n_at_kill = len(first_seen)
                killed = True
            if len(first_seen) >= len(reqs):
                break
            time.sleep(0.05)
        assert killed and n_at_kill < len(reqs), "no mid-load kill window"
        thread.join(timeout=120)
        assert not thread.is_alive(), "launcher never saw a job verdict"
        assert launcher.respawns >= 1, "agent 1 was never replaced"

        # exactly one terminal verdict per request, nothing extra
        results = {}
        for key in kv.keys("serve/result/"):
            rid = key[len("serve/result/"):]
            results[rid] = json.loads(kv.get(key))
        assert set(results) == {rid for rid, _, _ in reqs}
        ok = {r for r, v in results.items() if v["verdict"] == "ok"}
        shed = {r for r, v in results.items() if v["verdict"] == "SHED"}
        assert ok | shed == set(results) and not (ok & shed)
        # the undeadlined cohort can never legally shed; the
        # tighter-than-one-compile cohort guarantees sheds happened
        assert {r for r in shed if cohort[r] == "A"} == set()
        assert shed, "overload produced no sheds — not an overload"
        for r in shed:
            assert results[r]["reason"], results[r]
        # no ok verdict materially past its deadline (engine-clock
        # lateness becomes a SHED in _retire; the slack covers publish
        # tick + monitor poll latency only)
        for r in ok:
            if deadlines[r] is not None and r in first_seen:
                assert first_seen[r] <= deadlines[r] + 2.0, \
                    (r, first_seen[r] - deadlines[r])
        # everything that did complete is bitwise-identical to the
        # unfaulted greedy reference — chaos may shed, never corrupt
        want = _greedy_reference([q for q in reqs if q[0] in ok])
        for r in ok:
            assert results[r]["tokens"] == want[r], r
        # and the kill really exercised the requeue machinery
        assert int(kv.get(R.K_TAIL)) > len(reqs)
    finally:
        if thread.is_alive():
            kv.set(K_JOB_DONE, json.dumps(
                {"ok": False, "reason": "test teardown"}))
            thread.join(timeout=60)
        kv.close()
        server.stop()


def test_bench_serve_cli_prints_one_json_line():
    """The `bench.py --metric serve --quick` CLI path end to end in a
    fresh interpreter (the tier-1 smoke calls bench_serve in-process)."""
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"),
         "--metric", "serve", "--quick"],
        capture_output=True, text=True, timeout=300, cwd=root,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "serve"
    assert out["outputs_match"] is True


def test_bench_serve_slo_cli_prints_one_json_line():
    """`bench.py --metric serve_slo --quick` end to end: the calibrated
    overload comparison runs and reports its guardrail claims. Quick mode
    is too small for the claims to be meaningful, so only their presence
    and the accounting invariant are asserted here; BENCH_r06.json holds
    a committed full run."""
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"),
         "--metric", "serve_slo", "--quick"],
        capture_output=True, text=True, timeout=300, cwd=root,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "serve_slo"
    assert out["every_request_verdicted"] is True
    g = out["guarded_overload"]
    assert g["completed"] + g["shed"] == out["requests"]


if __name__ == "__main__":
    if "--serve-agent" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--serve-agent"]
        sys.exit(_agent_main(argv))
    sys.exit(2)
