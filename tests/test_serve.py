"""Serving stack, fast: paged KV allocator units, prefix sharing, the
bitwise decode-vs-forward parity contract, engine-vs-reference greedy
outputs (continuous AND static, including under preemption pressure),
the in-process replica protocol (drain/requeue, cross-worker completion,
lease-expiry scavenge), and the chipless `bench.py --metric serve` smoke.

The parity reference is the one-shot ``TransformerLM`` forward evaluated
at the cache's ``max_context`` padding — the same k-axis length the
decode softmax reduces over. Exact-length forwards match bitwise only
while the context is at or under XLA:CPU's unrolled-reduce threshold
(16); see serve/decode.py's module docstring for the full discipline.

The replica gang under real HostAgents (kill a replica mid-load, lose
nothing) runs slow in test_serve_integration.py.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
from tpu_sandbox.serve import (
    CacheConfig,
    ContinuousEngine,
    PagedKVCache,
    Request,
    ServeConfig,
    StaticEngine,
)
from tpu_sandbox.serve.decode import build_decode_step, init_pages

MCFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_len=128, dtype=jnp.float32)
CCFG = CacheConfig(num_blocks=24, block_size=4, max_blocks_per_seq=8)
MAX_CTX = CCFG.max_context  # 32


@pytest.fixture(scope="module")
def model():
    return TransformerLM(MCFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"]


@pytest.fixture(scope="module")
def step(params):
    """One compiled step set shared by every fp32 test in the module."""
    return build_decode_step(MCFG, CCFG, max_batch=3, buckets=(8, 16))


@pytest.fixture(scope="module")
def fwd32(model, params):
    """One-shot forward at max_context padding — THE parity reference."""
    return jax.jit(lambda toks: model.apply({"params": params}, toks))


@pytest.fixture(scope="module")
def greedy(fwd32):
    """Greedy continuation via the padded one-shot forward. One compiled
    shape total, and bitwise-identical logits to what the serve decode
    path computes — this IS the unfaulted reference output."""
    def _greedy(prompt, max_new):
        toks = list(prompt)
        out = []
        for _ in range(max_new):
            padded = np.zeros((1, MAX_CTX), np.int32)
            padded[0, :len(toks)] = toks
            logits = np.asarray(fwd32(jnp.asarray(padded)))[0, len(toks) - 1]
            t = int(logits.argmax())
            out.append(t)
            toks.append(t)
        return out
    return _greedy


def _scfg(**over):
    base = dict(model=MCFG, cache=CCFG, max_batch=3, buckets=(8, 16))
    base.update(over)
    return ServeConfig(**base)


# -- paged allocator units (no jax) ----------------------------------------


def test_cache_blocks_needed_and_admission():
    cache = PagedKVCache(CCFG)
    assert cache.blocks_needed([1] * 4, 0) == 1
    assert cache.blocks_needed([1] * 4, 1) == 2
    assert cache.blocks_needed([1] * 5, 11) == 4
    # 23 usable blocks (block 0 is the null block): a 24-block ask is out
    assert cache.alloc(list(range(5)), 0) is not None
    big = CacheConfig(num_blocks=4, block_size=4, max_blocks_per_seq=8)
    tight = PagedKVCache(big)
    assert tight.alloc([1] * 12, 0) is not None  # 3 blocks: exactly fits
    assert tight.alloc([2] * 4, 0) is None       # nothing left


def test_cache_free_list_reuse_and_grow():
    cfg = CacheConfig(num_blocks=6, block_size=4, max_blocks_per_seq=4)
    cache = PagedKVCache(cfg)
    a = cache.alloc([1, 2, 3, 4, 5], 0)          # 2 blocks
    b = cache.alloc([9, 8, 7], 0)                # 1 block
    assert len(a.block_ids) == 2 and len(b.block_ids) == 1
    assert cache.grow(a)                          # free 2 -> a takes one
    assert len(a.block_ids) == 3
    assert cache.grow(b)                          # b takes the last one
    cache.free(a, cache_prefix=False)
    c = cache.alloc([4] * 10, 0)                  # reuses a's freed blocks
    assert c is not None and len(c.block_ids) == 3
    cache.free(b, cache_prefix=False)
    cache.free(c, cache_prefix=False)
    assert cache.alloc([5] * 16, 0) is not None   # 4 blocks: pool healthy


def test_cache_prefix_sharing_refcounts_and_eviction():
    cfg = CacheConfig(num_blocks=6, block_size=4, max_blocks_per_seq=4)
    cache = PagedKVCache(cfg)                     # 5 usable blocks
    prompt = [7, 7, 7, 7, 5, 5, 5, 5, 9]          # two full blocks + tail
    a = cache.alloc(prompt, 0)
    assert a.n_shared == 0
    cache.commit_prefix(a)
    b = cache.alloc(prompt, 0)                    # full blocks shared
    assert b.n_shared == 2
    assert b.block_ids[:2] == a.block_ids[:2]
    assert cache.stats["prefix_hits"] == 1
    assert cache.stats["prefix_blocks_reused"] == 2
    cache.free(a)
    cache.free(b)
    # freed-with-prefix blocks stay cached (2) leaving 3 plainly free; a
    # 4-block ask only fits by evicting from the prefix cache
    c = cache.alloc([1] * 16, 0)
    assert c is not None
    assert cache.stats["evicted_cache_blocks"] >= 1


# -- bitwise parity ---------------------------------------------------------


def test_decode_matches_padded_forward_bitwise_fp32(params, step, fwd32):
    """Prefill + 24 decode steps, every step's logits bitwise equal to the
    one-shot forward at max_context padding (fp32, CPU)."""
    cache = PagedKVCache(CCFG)
    kp, vp = init_pages(MCFG, CCFG)
    prompt = [5, 17, 3, 42, 9]

    def ref_logits(seq):
        padded = np.zeros((1, MAX_CTX), np.int32)
        padded[0, :len(seq)] = seq
        return np.asarray(fwd32(jnp.asarray(padded)))[0, len(seq) - 1]

    alloc = cache.alloc(prompt, 0)
    toks = np.zeros((1, 8), np.int32)
    toks[0, :len(prompt)] = prompt
    dest = cache.dest_indices(alloc, 8).astype(np.int32)
    cur, kp, vp = step.prefill[8](
        params, kp, vp, jnp.asarray(toks), jnp.asarray(dest),
        jnp.asarray(len(prompt) - 1, jnp.int32))
    alloc.length = len(prompt)
    cur = np.asarray(cur)
    seq = list(prompt)
    assert np.array_equal(cur, ref_logits(seq)), "prefill logits diverged"

    for i in range(24):
        token = int(cur.argmax())
        seq.append(token)
        if alloc.length % CCFG.block_size == 0 \
                and alloc.length // CCFG.block_size >= len(alloc.block_ids):
            assert cache.grow(alloc)
        tokens = np.zeros((3, 1), np.int32)
        lengths = np.zeros((3,), np.int32)
        tables = np.zeros((3, CCFG.max_blocks_per_seq), np.int32)
        tokens[0, 0] = token
        lengths[0] = len(seq)
        tables[0] = cache.block_table(alloc)
        cur, kp, vp = step.decode(
            params, kp, vp, jnp.asarray(tokens), jnp.asarray(lengths),
            jnp.asarray(tables))
        cur = np.asarray(cur)[0]
        alloc.length = len(seq)
        ref = cur == ref_logits(seq)
        assert ref.all(), f"decode step {i} (context {len(seq)}) diverged"
        if len(seq) == 12:
            # spot-check the documented exact-length equality for n <= 16
            exact = np.asarray(
                jax.jit(lambda t: TransformerLM(MCFG).apply(
                    {"params": params}, t))(
                    jnp.asarray([seq], jnp.int32)))[0, -1]
            assert np.array_equal(cur, exact)
    cache.free(alloc, cache_prefix=False)


def test_decode_bf16_cache_stays_close(params, fwd32):
    """With a bf16 KV cache the bitwise contract relaxes to tolerance —
    the cache quantization is the only difference (params stay fp32)."""
    step16 = build_decode_step(MCFG, CCFG, max_batch=2, buckets=(8,),
                               cache_dtype=jnp.bfloat16)
    cache = PagedKVCache(CCFG)
    kp, vp = init_pages(MCFG, CCFG, jnp.bfloat16)
    prompt = [11, 2, 33, 4]
    alloc = cache.alloc(prompt, 0)
    toks = np.zeros((1, 8), np.int32)
    toks[0, :len(prompt)] = prompt
    dest = cache.dest_indices(alloc, 8).astype(np.int32)
    cur, kp, vp = step16.prefill[8](
        params, kp, vp, jnp.asarray(toks), jnp.asarray(dest),
        jnp.asarray(len(prompt) - 1, jnp.int32))
    alloc.length = len(prompt)
    seq = list(prompt)
    for _ in range(12):
        token = int(np.asarray(cur).argmax())
        seq.append(token)
        if alloc.length % CCFG.block_size == 0 \
                and alloc.length // CCFG.block_size >= len(alloc.block_ids):
            assert cache.grow(alloc)
        tokens = np.zeros((2, 1), np.int32)
        lengths = np.zeros((2,), np.int32)
        tables = np.zeros((2, CCFG.max_blocks_per_seq), np.int32)
        tokens[0, 0] = token
        lengths[0] = len(seq)
        tables[0] = cache.block_table(alloc)
        cur, kp, vp = step16.decode(
            params, kp, vp, jnp.asarray(tokens), jnp.asarray(lengths),
            jnp.asarray(tables))
        cur = np.asarray(cur)[0]
        alloc.length = len(seq)
        padded = np.zeros((1, MAX_CTX), np.int32)
        padded[0, :len(seq)] = seq
        ref = np.asarray(fwd32(jnp.asarray(padded)))[0, len(seq) - 1]
        np.testing.assert_allclose(cur, ref, rtol=0.05, atol=0.05)
    cache.free(alloc, cache_prefix=False)


# -- engines vs reference ---------------------------------------------------


def _requests(rng, n, *, lo=3, hi=13, new_lo=4, new_hi=10):
    out = []
    for i in range(n):
        prompt = [int(t) for t in rng.integers(1, 64,
                                               size=int(rng.integers(lo, hi)))]
        out.append(Request(rid=f"r{i}", prompt=prompt,
                           max_new_tokens=int(rng.integers(new_lo, new_hi))))
    return out


def test_continuous_and_static_match_reference(params, step, greedy):
    rng = np.random.default_rng(1)
    reqs = _requests(rng, 8)
    want = {r.rid: greedy(r.prompt, r.max_new_tokens) for r in reqs}
    for engine_cls in (ContinuousEngine, StaticEngine):
        eng = engine_cls(params, _scfg(), step=step)
        for r in reqs:
            eng.submit(Request(rid=r.rid, prompt=list(r.prompt),
                               max_new_tokens=r.max_new_tokens))
        eng.run_until_idle()
        got = {rid: res.tokens for rid, res in eng.results.items()}
        assert got == want, engine_cls.__name__
        assert all(res.ttft >= 0 for res in eng.results.values())


def test_prefix_sharing_preserves_outputs(params, step, greedy):
    """Duplicate prompts share prefix blocks (observable in stats) and the
    outputs stay identical to the reference — sharing is invisible."""
    rng = np.random.default_rng(2)
    prompt = [int(t) for t in rng.integers(1, 64, size=9)]
    eng = ContinuousEngine(params, _scfg(), step=step)
    eng.submit(Request(rid="a", prompt=list(prompt), max_new_tokens=6))
    eng.run_until_idle()
    eng.submit(Request(rid="b", prompt=list(prompt), max_new_tokens=6))
    eng.run_until_idle()
    assert eng.cache.stats["prefix_hits"] >= 1
    want = greedy(prompt, 6)
    assert eng.results["a"].tokens == want
    assert eng.results["b"].tokens == want


def test_preemption_under_block_pressure_replays_identically(params, step,
                                                             greedy):
    """A cache too small for the admitted set forces preempt-to-requeue
    across block-table eviction and re-admission; greedy replay makes the
    final outputs identical to the unpressured reference anyway."""
    rng = np.random.default_rng(3)
    # three DISTINCT 12-token prompts (distinct so prefix sharing can't
    # collapse their block usage), each decoding to the 32-token context
    # ceiling: all three slots march in lockstep toward 8 blocks apiece,
    # and 3 x 8 = 24 > 23 usable blocks guarantees one grow() fails
    reqs = [Request(rid=f"r{i}",
                    prompt=[int(t) for t in rng.integers(1, 64, size=12)],
                    max_new_tokens=20)
            for i in range(3)]
    eng = ContinuousEngine(params, _scfg(), step=step)
    for r in reqs:
        eng.submit(r)
    eng.run_until_idle()
    assert sum(res.preemptions for res in eng.results.values()) >= 1, \
        "pressure case produced no preemption; shrink the pool"
    for r in reqs:
        assert eng.results[r.rid].tokens == greedy(r.prompt,
                                                   r.max_new_tokens), r.rid


# -- replica protocol (in-process) -----------------------------------------


def _submit_all(kv, reqs):
    from tpu_sandbox.serve import replica as R

    for r in reqs:
        R.submit_request(kv, r.rid, r.prompt, r.max_new_tokens)
    R.announce_total(kv, len(reqs))


def test_replica_drain_requeues_and_peer_finishes(params, step, greedy):
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve import replica as R

    server = KVServer()
    kv = KVClient(port=server.port)
    try:
        rng = np.random.default_rng(4)
        reqs = _requests(rng, 6)
        _submit_all(kv, reqs)
        w1 = R.ReplicaWorker(kv, ContinuousEngine(params, _scfg(),
                                                  step=step),
                             tag="w1", lease_ttl=0.5)
        for _ in range(3):
            w1.tick()
        assert w1.stats.claimed >= 1
        w1.request_drain()           # the SIGTERM path
        w1.tick()
        assert w1.stats.requeued + w1.stats.completed >= w1.stats.claimed
        w2 = R.ReplicaWorker(kv, ContinuousEngine(params, _scfg(),
                                                  step=step),
                             tag="w2", lease_ttl=0.5)
        w2.run(timeout=60)
        for r in reqs:
            res = R.read_result(kv, r.rid, timeout=5)
            assert res["tokens"] == greedy(r.prompt, r.max_new_tokens), r.rid
    finally:
        kv.close()
        server.stop()


def test_replica_scavenge_rescues_orphaned_claims(params, step, greedy):
    """A claimant that vanishes without draining (SIGKILL) leaves claims
    whose leases expire; a peer's scavenge pass requeues them exactly once
    and the job still completes with reference outputs."""
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve import replica as R

    server = KVServer()
    kv = KVClient(port=server.port)
    try:
        rng = np.random.default_rng(5)
        reqs = _requests(rng, 4)
        _submit_all(kv, reqs)
        dead = R.ReplicaWorker(kv, ContinuousEngine(params, _scfg(),
                                                    step=step),
                               tag="dead", lease_ttl=0.3)
        dead.tick()                  # claims + leases, then goes silent
        assert dead.stats.claimed >= 1
        dead.engine.drain_to_requests()  # drop its work on the floor
        time.sleep(0.5)              # leases expire unheartbeaten
        w = R.ReplicaWorker(kv, ContinuousEngine(params, _scfg(),
                                                 step=step),
                            tag="rescuer", lease_ttl=0.5,
                            scavenge_interval=0.1)
        w.run(timeout=60)
        assert w.stats.scavenged >= 1
        for r in reqs:
            res = R.read_result(kv, r.rid, timeout=5)
            assert res["tokens"] == greedy(r.prompt, r.max_new_tokens), r.rid
    finally:
        kv.close()
        server.stop()


def test_sampled_decode_interrupted_mid_decode_replays_bitwise(params, step):
    """Replay-exact sampling through a kill: a temperature/top-k request is
    claimed, decoded partway, then its worker drains (the SIGTERM path) and
    a peer re-executes it from scratch — the final tokens are bitwise
    identical to an uninterrupted run, because each sampled step draws from
    ``fold_in(key(seed), step_index)``, not from mutable sampler state."""
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve import replica as R

    rng = np.random.default_rng(6)
    prompt = [int(t) for t in rng.integers(1, 64, size=9)]
    kwargs = dict(max_new_tokens=12, temperature=3.0, top_k=8, seed=7)

    # the uninterrupted reference run, and proof the sampler is live
    ref = ContinuousEngine(params, _scfg(), step=step)
    ref.submit(Request(rid="ref", prompt=list(prompt), **kwargs))
    ref.run_until_idle()
    want = ref.results["ref"].tokens
    greedy_eng = ContinuousEngine(params, _scfg(), step=step)
    greedy_eng.submit(Request(rid="g", prompt=list(prompt),
                              max_new_tokens=12))
    greedy_eng.run_until_idle()
    assert want != greedy_eng.results["g"].tokens, \
        "temperature-3.0 sampling reproduced greedy — sampler not engaged"

    server = KVServer()
    kv = KVClient(port=server.port)
    try:
        R.submit_request(kv, "s", prompt, 12, temperature=3.0, top_k=8,
                         seed=7)
        R.announce_total(kv, 1)
        w1 = R.ReplicaWorker(kv, ContinuousEngine(params, _scfg(),
                                                  step=step),
                             tag="w1", lease_ttl=0.5)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            w1.tick()
            slots = [s for s in w1.engine.slots
                     if s is not None and s.request.rid == "s"]
            if slots and len(slots[0].generated) >= 3:
                break
        assert slots and 3 <= len(slots[0].generated) < 12, \
            "no mid-decode window"
        w1.request_drain()
        w1.tick()
        assert w1.stats.requeued == 1
        w2 = R.ReplicaWorker(kv, ContinuousEngine(params, _scfg(),
                                                  step=step),
                             tag="w2", lease_ttl=0.5)
        w2.run(timeout=60)
        assert R.read_result(kv, "s", timeout=5)["tokens"] == want
    finally:
        kv.close()
        server.stop()


# -- bench smoke ------------------------------------------------------------


def test_bench_serve_quick_smoke():
    """`bench_serve(quick=True)` is chipless and must report the SLO
    fields and reference-identical outputs across the two scheduling
    policies. In-process on purpose: a subprocess pays ~2s of fresh jax
    startup for no extra coverage (the CLI path is exercised in the slow
    test_serve_integration.py)."""
    from bench import bench_serve

    out = bench_serve(quick=True)
    assert out["metric"] == "serve"
    assert out["outputs_match"] is True
    for side in ("continuous", "static"):
        for field in ("tokens_per_sec", "p50_ttft_ms", "p99_ttft_ms",
                      "p50_itl_ms", "p99_itl_ms"):
            assert out[side][field] >= 0, (side, field)
