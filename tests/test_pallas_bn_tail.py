"""fused_bn_relu_pool == the unfused _GroupedBN + relu + block_max_pool.

Pins the contract that lets ConvNetS2D(fused_tail=True) swap the Pallas
tail in: identical pooled output, batch stats, and gradients (y, gamma,
beta) vs the jnp chain, for both layer shapes (blk=4/co small, blk=2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.ops.pallas_bn_tail import (
    fused_bn_relu_pool,
    unfused_reference as ref_chain,
)


@pytest.mark.parametrize("blk,co,hw", [(4, 4, 12), (2, 16, 8), (4, 16, 8)])
def test_forward_matches_unfused(blk, co, hw):
    rng = np.random.default_rng(0)
    c = blk * blk * co
    y = jnp.asarray(rng.standard_normal((2, hw, hw, c)), jnp.float32)
    gamma = jnp.asarray(rng.standard_normal(co), jnp.float32)
    beta = jnp.asarray(rng.standard_normal(co), jnp.float32)
    out, mu, var = fused_bn_relu_pool(y, gamma, beta, co, blk)
    ref, mu_r, var_r = ref_chain(y, gamma, beta, co, blk)
    np.testing.assert_allclose(np.asarray(mu), np.asarray(mu_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), np.asarray(var_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("blk,co", [(4, 4), (2, 16)])
def test_gradients_match_unfused(blk, co):
    rng = np.random.default_rng(1)
    c = blk * blk * co
    y = jnp.asarray(rng.standard_normal((2, 8, 8, c)), jnp.float32)
    gamma = jnp.asarray(1 + 0.1 * rng.standard_normal(co), jnp.float32)
    beta = jnp.asarray(rng.standard_normal(co), jnp.float32)
    cot = jnp.asarray(
        rng.standard_normal((2, 8, 8, (blk // 2) ** 2 * co)), jnp.float32
    )

    def loss_fused(y, gamma, beta):
        out, _, _ = fused_bn_relu_pool(y, gamma, beta, co, blk)
        return jnp.sum(out * cot)

    def loss_ref(y, gamma, beta):
        out, _, _ = ref_chain(y, gamma, beta, co, blk)
        return jnp.sum(out * cot)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(y, gamma, beta)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(y, gamma, beta)
    for name, a, b in zip(("dy", "dgamma", "dbeta"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, err_msg=name
        )


def test_bf16_forward_close():
    rng = np.random.default_rng(2)
    co, blk = 16, 4
    c = blk * blk * co
    y = jnp.asarray(rng.standard_normal((1, 8, 8, c)), jnp.bfloat16)
    gamma = jnp.ones(co, jnp.float32)
    beta = jnp.zeros(co, jnp.float32)
    out, _, _ = fused_bn_relu_pool(y, gamma, beta, co, blk)
    ref, _, _ = ref_chain(y, gamma, beta, co, blk)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


def test_bf16_tie_gradients_match_unfused():
    """bf16 rounding creates exact pool ties; the kernel must split tied
    cotangents 0.5/0.5 like jnp.maximum's VJP, comparing values rounded to
    the activation dtype — winner-take-all would diverge here."""
    rng = np.random.default_rng(7)
    co, blk = 8, 2
    c = blk * blk * co
    # quantize the input so post-BN bf16 values tie often
    y = jnp.asarray(
        np.round(rng.standard_normal((2, 8, 8, c)) * 2) / 2, jnp.bfloat16
    )
    gamma = jnp.ones(co, jnp.float32)
    beta = jnp.zeros(co, jnp.float32)
    cot = jnp.asarray(
        rng.standard_normal((2, 8, 8, (blk // 2) ** 2 * co)), jnp.float32
    )

    def loss(fused):
        def f(y):
            if fused:
                out, _, _ = fused_bn_relu_pool(y, gamma, beta, co, blk)
            else:
                out, _, _ = ref_chain(y, gamma, beta, co, blk)
            return jnp.sum(out.astype(jnp.float32) * cot)
        return f

    gf = jax.grad(loss(True))(y)
    gr = jax.grad(loss(False))(y)
    # sanity: the test really exercises ties (some 0.5-weighted routing)
    assert float(jnp.sum(jnp.abs(gf.astype(jnp.float32)))) > 0
    np.testing.assert_allclose(
        np.asarray(gf, np.float32), np.asarray(gr, np.float32), atol=2e-2
    )
