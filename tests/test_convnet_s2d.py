"""ConvNetS2D == ConvNet: the space-to-depth plan is the same function.

The s2d model exists purely as an execution plan (models/convnet_s2d.py);
these tests pin the contract that lets bench.py and the entry scripts swap
it in for the reference-parity ConvNet: identical parameter tree, identical
forward, identical gradients, identical batch-stats evolution.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_sandbox.models import ConvNet
from tpu_sandbox.models.convnet_s2d import ConvNetS2D, scatter_kernel
from tpu_sandbox.ops.losses import cross_entropy_loss


def _models(use_bn=True, dtype=jnp.float32):
    return (ConvNet(use_bn=use_bn, dtype=dtype),
            ConvNetS2D(use_bn=use_bn, dtype=dtype))


def _data(n=3, hw=48, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n, hw, hw, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(n,)), jnp.int32)
    return x, y


def test_pick_convnet_plan_switch():
    from tpu_sandbox.models import pick_convnet, resolve_plan
    # on CPU (interpret-mode kernels) auto resolves to the NHWC s2d plan;
    # on TPU / forced-compile it resolves to the transposed plan
    assert type(pick_convnet(3000)).__name__ == "ConvNetS2D"
    assert type(pick_convnet(3000, plan="plain")).__name__ == "ConvNet"
    assert type(pick_convnet(3001)).__name__ == "ConvNet"  # not 4-divisible
    assert type(pick_convnet((128, 64))).__name__ == "ConvNetS2D"
    assert type(pick_convnet(3000, plan="s2dt")).__name__ == "ConvNetS2DT"
    from tpu_sandbox.ops.pallas_common import default_interpret
    # backend-dependent: interpret mode (CPU tests) -> NHWC s2d; compiled
    # (TPU / forced) -> transposed (ADVICE r03)
    assert resolve_plan(3000) == ("s2d" if default_interpret(None)
                                  else "s2dt")
    # and BOTH branches deterministically, via the force-compile override
    # (a regression hardcoding 's2d' must fail off-chip too)
    import os
    from unittest import mock

    with mock.patch.dict(os.environ,
                         {"TPU_SANDBOX_FORCE_COMPILED_KERNELS": "1"}):
        assert resolve_plan(3000) == "s2dt"
    # fused_conv=False must disable the Pallas convs even where 'auto'
    # resolves to the always-Pallas transposed plan
    assert type(pick_convnet(3000, plan="s2dt",
                             fused_conv=False)).__name__ == "ConvNetS2D"
    assert resolve_plan(3000, "s2dt") == "s2dt"
    assert resolve_plan(3001) == "plain"


def test_param_trees_compatible():
    ref, s2d = _models()
    x, _ = _data()
    vr = ref.init(jax.random.key(0), x)
    vs = s2d.init(jax.random.key(0), x)
    ref_shapes = jax.tree.map(jnp.shape, vr)
    s2d_shapes = jax.tree.map(jnp.shape, vs)
    assert ref_shapes == s2d_shapes


def test_scatter_kernel_reproduces_conv():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((5, 5, 1, 3)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 16, 16)), jnp.float32)
    ref = jax.lax.conv_general_dilated(
        x[..., None], w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    from tpu_sandbox.models.convnet_s2d import space_to_depth
    out = jax.lax.conv_general_dilated(
        space_to_depth(x, 4), scatter_kernel(w, 4), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    # undo s2d on the output: channel (a*4+b)*3+co at block (i,j)
    n, hb, wb, _ = out.shape
    out = out.reshape(n, hb, wb, 4, 4, 3).transpose(0, 1, 3, 2, 4, 5)
    out = out.reshape(n, hb * 4, wb * 4, 3)
    np.testing.assert_allclose(out, ref, atol=1e-5)


@pytest.mark.parametrize("use_bn", [True, False])
def test_forward_matches_convnet(use_bn):
    ref, s2d = _models(use_bn)
    x, _ = _data()
    variables = ref.init(jax.random.key(0), x)
    if use_bn:
        lr = ref.apply(variables, x, train=True, mutable=["batch_stats"])
        ls = s2d.apply(variables, x, train=True, mutable=["batch_stats"])
        out_r, out_s = lr[0], ls[0]
    else:
        out_r = ref.apply(variables, x, train=True)
        out_s = s2d.apply(variables, x, train=True)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_r),
                               atol=2e-4)
    if use_bn:
        for k in ("bn1", "bn2"):
            for stat in ("mean", "var"):
                np.testing.assert_allclose(
                    np.asarray(ls[1]["batch_stats"][k][stat]),
                    np.asarray(lr[1]["batch_stats"][k][stat]),
                    atol=1e-5, err_msg=f"{k}/{stat}")


def test_eval_mode_uses_running_stats():
    ref, s2d = _models()
    x, _ = _data()
    variables = ref.init(jax.random.key(0), x)
    out_r = ref.apply(variables, x, train=False)
    out_s = s2d.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_r),
                               atol=2e-4)


def test_gradients_match_convnet():
    ref, s2d = _models()
    x, y = _data()
    variables = ref.init(jax.random.key(0), x)
    params, stats = variables["params"], variables["batch_stats"]

    def loss_fn(model):
        def f(p):
            logits, _ = model.apply(
                {"params": p, "batch_stats": stats}, x, train=True,
                mutable=["batch_stats"],
            )
            return cross_entropy_loss(logits, y)
        return f

    lr, gr = jax.value_and_grad(loss_fn(ref))(params)
    ls, gs = jax.value_and_grad(loss_fn(s2d))(params)
    np.testing.assert_allclose(ls, lr, atol=1e-5)
    flat_r = jax.tree_util.tree_leaves_with_path(gr)
    flat_s = {jax.tree_util.keystr(k): v
              for k, v in jax.tree_util.tree_leaves_with_path(gs)}
    for k, v in flat_r:
        np.testing.assert_allclose(
            np.asarray(flat_s[jax.tree_util.keystr(k)]), np.asarray(v),
            atol=5e-4, err_msg=jax.tree_util.keystr(k))


def test_short_training_runs_stay_together():
    """5 SGD steps from shared init: losses track to float tolerance."""
    ref, s2d = _models()
    x, y = _data(n=4, hw=32)
    tx = optax.sgd(1e-2)
    variables = ref.init(jax.random.key(0), x)

    def run(model):
        params, stats = variables["params"], variables["batch_stats"]
        opt = tx.init(params)
        losses = []
        for _ in range(5):
            def f(p):
                logits, upd = model.apply(
                    {"params": p, "batch_stats": stats}, x, train=True,
                    mutable=["batch_stats"],
                )
                return cross_entropy_loss(logits, y), upd
            (loss, upd), g = jax.value_and_grad(f, has_aux=True)(params)
            stats = upd["batch_stats"]
            updates, opt = tx.update(g, opt, params)
            params = optax.apply_updates(params, updates)
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run(s2d), run(ref), rtol=1e-4)


@pytest.mark.parametrize(
    "fused_tail,fused_conv",
    [(False, False), (True, False), (True, True), (False, True)],
)
def test_s2d_under_data_parallel_matches_plain_model(mesh8, fused_tail,
                                                     fused_conv):
    """The headline-bench path: ConvNetS2D inside DataParallel over 8
    shards trains the same losses as ConvNet in the same engine (shared
    init; BN per-replica in both) — with and without the fused Pallas
    tail/conv, since pick_convnet defaults production entry points to
    both fused."""
    from tpu_sandbox.data import synthetic_mnist
    from tpu_sandbox.data.mnist import normalize
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.train import TrainState

    images, labels = synthetic_mnist(n=16, seed=0)
    images, labels = normalize(images), labels.astype("int32")
    tx = optax.sgd(1e-2)
    ref, _ = _models()
    s2d = ConvNetS2D(fused_tail=fused_tail, fused_conv=fused_conv)
    variables = ref.init(jax.random.key(0),
                         jnp.zeros((1, 32, 32, 1), jnp.float32))
    state0 = TrainState(
        step=jnp.zeros((), jnp.int32), params=variables["params"],
        batch_stats=variables["batch_stats"],
        opt_state=tx.init(variables["params"]),
    )

    def run(model):
        dp = DataParallel(model, tx, mesh8, image_size=(32, 32), donate=False)
        st = dp.shard_state(state0)
        losses = []
        for _ in range(3):
            st, loss = dp.train_step(st, *dp.shard_batch(images, labels))
            losses.append(np.asarray(loss))
        return losses

    np.testing.assert_allclose(
        np.stack(run(s2d)), np.stack(run(ref)), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("fused_conv", [False, True])
def test_fused_tail_matches_unfused_model(fused_conv):
    """ConvNetS2D(fused_tail=True[, fused_conv=True]) == ConvNetS2D:
    logits, grads, and BN running stats with shared init."""
    x, y = _data(n=2, hw=32, seed=5)
    plain = ConvNetS2D()
    fused = ConvNetS2D(fused_tail=True, fused_conv=fused_conv)
    variables = plain.init(jax.random.key(0), x)
    params, stats = variables["params"], variables["batch_stats"]

    def step(model, params, stats):
        def f(p):
            logits, upd = model.apply(
                {"params": p, "batch_stats": stats}, x, train=True,
                mutable=["batch_stats"],
            )
            return cross_entropy_loss(logits, y), upd
        (loss, upd), g = jax.value_and_grad(f, has_aux=True)(params)
        return loss, g, upd["batch_stats"]

    lp, gp, sp = step(plain, params, stats)
    lf, gf, sf = step(fused, params, stats)
    np.testing.assert_allclose(float(lf), float(lp), atol=1e-5)
    for (kp, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(gp),
        jax.tree_util.tree_leaves_with_path(gf),
    ):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-4,
            err_msg=jax.tree_util.keystr(kp),
        )
    for (kp, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(sp),
        jax.tree_util.tree_leaves_with_path(sf),
    ):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-5,
            err_msg=jax.tree_util.keystr(kp),
        )
