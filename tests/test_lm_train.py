"""lm_train entry script: every parallelism trains and the loss drops.

Runs the script's train() in-process on the conftest's 8-device virtual CPU
mesh (tiny configs — the script itself raises SystemExit if the loss does
not decrease, so convergence is part of the contract under test).
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import lm_train  # noqa: E402


def _args(**over):
    """Complete args from the real parser (new flags inherit CLI defaults),
    with the small-shape test base applied on top."""
    args = lm_train.build_parser().parse_args([])
    base = dict(
        parallelism="dp", devices=4, steps=24, batch=4, seq_len=32, vocab=16,
        d_model=16, n_heads=2, n_layers=2, d_ff=32, lr=1e-2, microbatches=2,
        log_every=8, dtype="fp32", attn="ring", flash=False, remat=False,
        force_cpu=False, dp=1, circular_chunks=1, router_top_k=1,
    )
    base.update(over)
    for k, v in base.items():
        setattr(args, k, v)
    return args


@pytest.mark.parametrize("parallelism", ["dp", "tp", "sp", "ep"])
def test_parallelism_trains(parallelism, devices):
    # tp shards the head and d_ff dims over 4 devices -> need 4 heads
    heads = 4 if parallelism == "tp" else 2
    lm_train.train(_args(parallelism=parallelism, n_heads=heads))


def test_pp_trains(devices):
    lm_train.train(_args(parallelism="pp", n_layers=4, devices=4))


def test_pp_circular_trains(devices):
    lm_train.train(_args(parallelism="pp", n_layers=8, devices=4,
                         microbatches=4, circular_chunks=2))


def test_ep_top2_trains(devices):
    lm_train.train(_args(parallelism="ep", router_top_k=2))


def test_tp_composes_with_dp(devices):
    # data=2 x model=4: the full megatron ruleset under a composed mesh
    lm_train.train(_args(parallelism="tp", devices=8, dp=2, n_heads=4,
                         vocab=16, batch=4))


def test_3d_mesh_trains(devices):
    # data=2 x model=2 x pipe=2: TP stages inside the pipeline
    lm_train.train(_args(parallelism="3d", devices=8, n_layers=2, batch=4))


def test_remat_matches_plain(devices, capsys):
    lm_train.train(_args(steps=8, log_every=4))
    plain = capsys.readouterr().out
    lm_train.train(_args(steps=8, log_every=4, remat=True))
    remat = capsys.readouterr().out
    # remat changes memory, not math: identical logged losses
    pick = lambda s: [l for l in s.splitlines() if "Loss" in l]  # noqa: E731
    assert pick(plain) == pick(remat)
