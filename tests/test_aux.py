"""Aux subsystems: checkpoint round-trip + resume, metrics writer,
step timer, and the Pallas fused CE kernel (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_sandbox.models import ConvNet
from tpu_sandbox.train import TrainState, make_train_step
from tpu_sandbox.train import checkpoint as ckpt
from tpu_sandbox.utils.metrics import MetricsWriter, read_metrics
from tpu_sandbox.utils.profiling import StepTimer


def small_state(lr=0.05):
    model = ConvNet()
    tx = optax.sgd(lr)
    state = TrainState.create(model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx)
    return model, tx, state


def test_checkpoint_roundtrip(tmp_path):
    model, tx, state = small_state()
    step_fn = make_train_step(model, tx, donate=False)
    from tpu_sandbox.data import synthetic_mnist
    from tpu_sandbox.data.mnist import normalize

    images, labels = synthetic_mnist(n=8)
    state, _ = step_fn(state, jnp.asarray(normalize(images)), jnp.asarray(labels.astype("int32")))

    saved_step = ckpt.save(tmp_path / "ck", state)
    assert saved_step == 1
    assert ckpt.latest_step(tmp_path / "ck") == 1

    _, _, template = small_state()
    restored = ckpt.restore(tmp_path / "ck", template)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        state, restored,
    )
    # resume: training continues from the restored state identically
    s1, l1 = step_fn(state, jnp.asarray(normalize(images)), jnp.asarray(labels.astype("int32")))
    s2, l2 = step_fn(restored, jnp.asarray(normalize(images)), jnp.asarray(labels.astype("int32")))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-7)


def test_checkpoint_restore_missing_raises(tmp_path):
    _, _, template = small_state()
    with pytest.raises(FileNotFoundError):
        ckpt.restore(tmp_path / "empty", template)


def test_metrics_writer_roundtrip(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsWriter(path) as w:
        w.write(1, loss=1.5, note="a")
        w.write(2, loss=jnp.asarray(0.75))
    records = read_metrics(path)
    assert [r["step"] for r in records] == [1, 2]
    assert records[1]["loss"] == 0.75


def test_step_timer():
    import time

    t = StepTimer(warmup=1)
    t.start()
    for _ in range(4):
        time.sleep(0.01)
        t.tick(n_items=10)
    assert 0.005 < t.seconds_per_step < 0.1
    assert t.items_per_second > 50


def test_pallas_ce_matches_reference():
    from tpu_sandbox.ops.losses import cross_entropy_loss
    from tpu_sandbox.ops.pallas_ce import pallas_cross_entropy

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(37, 10)).astype(np.float32)) * 3
    labels = jnp.asarray(rng.integers(0, 10, size=37).astype(np.int32))
    ref = cross_entropy_loss(logits, labels)
    got = pallas_cross_entropy(logits, labels)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)


def test_pallas_ce_gradient_matches():
    from tpu_sandbox.ops.losses import cross_entropy_loss
    from tpu_sandbox.ops.pallas_ce import pallas_cross_entropy

    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(16, 64)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 64, size=16).astype(np.int32))
    g_ref = jax.grad(lambda l: cross_entropy_loss(l, labels))(logits)
    g_got = jax.grad(lambda l: pallas_cross_entropy(l, labels))(logits)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref), atol=1e-6)


def test_pallas_ce_large_vocab_block_grid():
    from tpu_sandbox.ops.losses import cross_entropy_loss
    from tpu_sandbox.ops.pallas_ce import pallas_cross_entropy

    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(300, 257)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 257, size=300).astype(np.int32))
    np.testing.assert_allclose(
        float(pallas_cross_entropy(logits, labels)),
        float(cross_entropy_loss(logits, labels)),
        rtol=1e-6,
    )


def test_bench_is_oom_matcher():
    """bench._is_oom must catch every allocator-failure phrasing seen in the
    wild: PJRT RESOURCE_EXHAUSTED, generic OOM, and the axon remote
    compiler's AOT 'would exceed memory'."""
    import bench

    assert bench._is_oom("RESOURCE_EXHAUSTED: out of memory allocating")
    assert bench._is_oom("XlaRuntimeError: Allocation (size=18432000000) "
                         "would exceed memory (size=17179869184)")
    assert bench._is_oom("oom while allocating")
    assert not bench._is_oom("ValueError: shapes do not match")


def test_pallas_ce_huge_vocab_falls_back_to_jnp():
    """Beyond ~128k vocab no row block fits the VMEM budget; the call must
    fall back to the jnp loss with identical value and gradient."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_sandbox.ops.losses import cross_entropy_loss
    from tpu_sandbox.ops.pallas_ce import _block_rows, pallas_cross_entropy

    assert _block_rows(512 * 1024) is None
    assert _block_rows(32768) == 32
    assert _block_rows(1024) == 128
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(8, 200000)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 200000, size=(8,)), jnp.int32)
    v, g = jax.value_and_grad(pallas_cross_entropy)(logits, labels)
    v_ref, g_ref = jax.value_and_grad(cross_entropy_loss)(logits, labels)
    np.testing.assert_allclose(float(v), float(v_ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-7)


def test_bench_plan_ladder():
    """The bench's execution-plan fallback ladder (bench.py): first
    working rung wins; fallback rungs are labeled with the triggering
    error; total failure returns a degraded record, never raises."""
    import sys
    sys.path.insert(0, ".")
    from bench import run_plan_ladder

    # first rung works
    r = run_plan_ladder(lambda o: {"value": 1, "overrides": dict(o)})
    assert r["value"] == 1 and "plan_fallback" not in r

    # fused plans fail, unfused rung succeeds and is labeled
    def run(overrides):
        if overrides.get("fused_conv", True):
            raise RuntimeError("Mosaic says no")
        return {"value": 2, "overrides": dict(o := overrides)}

    r = run_plan_ladder(run)
    assert r["value"] == 2
    assert "Mosaic says no" in r["plan_fallback"]
    assert "conv kernels disabled" in r["plan_fallback"]

    # everything fails: degraded record, no exception
    def boom(overrides):
        raise ValueError("total kernel failure")

    r = run_plan_ladder(boom)
    assert r["value"] == 0.0
    assert "total kernel failure" in r["degraded"]

    # rung dedup: --plan s2d makes the transposed rung byte-identical to
    # the first; it must not be re-run (code-review r03 finding)
    calls = []

    def record(overrides):
        calls.append(dict(overrides))
        raise RuntimeError("fail every rung")

    run_plan_ladder(record, plan="s2d")
    assert calls == [{}, {"plan": "s2d", "fused_conv": False},
                     {"plan": "s2d", "fused_conv": False,
                      "fused_tail": False}]

    # an explicit plain request is never escalated to an s2d rung
    calls.clear()
    run_plan_ladder(record, plan="plain")
    assert calls == [{}]

    # under the transposed plan, the r05 fused conv1 backward gets its
    # own rung BEFORE the plan is abandoned (a compile failure in the
    # one never-on-chip kernel must not cost the whole s2dt headline);
    # on other plans that rung dedups away (covered by the s2d/plain
    # sequences above)
    calls.clear()
    run_plan_ladder(record, plan="s2dt")
    assert calls == [{}, {"fused_conv1_bwd": False},
                     {"plan": "s2d"},
                     {"plan": "s2d", "fused_conv": False},
                     {"plan": "s2d", "fused_conv": False,
                      "fused_tail": False}]


def test_bench_loss_gate_flags_divergence_and_nan():
    """The loss-plausibility gate (VERDICT r03 next-3): sane losses pass
    untouched; divergent, NaN, and inf losses get the loss_flag, and
    non-finite values are stringified so the JSON line stays standard."""
    from bench import annotate_loss

    r = {}
    annotate_loss(r, 2.3)
    assert "loss_flag" not in r

    r = {}
    annotate_loss(r, 10.1)
    assert "divergence" in r["loss_flag"]

    for bad in (float("nan"), float("inf"), float("-inf")):
        r = {"final_loss": bad}
        annotate_loss(r, bad)
        assert "loss_flag" in r
        assert isinstance(r["final_loss"], str)  # json-standard


def test_measure_per_step_repeated_min_and_spread():
    """Repeat protocol (VERDICT r03 next-7): min published with per-sample
    spread; any noise-negative repeat voids the spread claim and is
    counted, never averaged in."""
    from tpu_sandbox.utils.profiling import measure_per_step_repeated

    times = iter([0.040, 0.050, 0.045])
    import tpu_sandbox.utils.profiling as prof

    def fake(run_steps, n):
        return {"sec_per_step": next(times), "t_n_sec": 0.0,
                "t_2n_sec": 0.0, "n": n, "timing_method": "fake"}

    orig = prof.measure_per_step
    prof.measure_per_step = fake
    try:
        out = measure_per_step_repeated(lambda k: None, 2, repeats=3)
        assert out["sec_per_step"] == 0.040
        assert out["spread_frac"] == 0.25
        assert "nonpositive_samples" not in out

        times = iter([-0.001, 0.040, -0.002])
        out = measure_per_step_repeated(lambda k: None, 2, repeats=3)
        assert out["sec_per_step"] == 0.040
        assert out["spread_frac"] is None  # one sample is NOT repeatability
        assert out["nonpositive_samples"] == 2
    finally:
        prof.measure_per_step = orig


def test_hlo_traffic_classify_tags():
    """tools/hlo_traffic.py classify: the r04 input-stage class, conv
    fwd/bwd provenance, the pallas fallback, and the no-provenance copy
    bucket (the attribution the round-4 step surgery was driven by)."""
    import importlib
    import sys

    sys.path.insert(0, "tools")
    ht = importlib.import_module("hlo_traffic")

    def line(op_name, extra=""):
        return (f'  %x = bf16[1] fusion(%a), {extra}'
                f'metadata={{{{op_name="jit(train_step)/{op_name}"}}}}')

    assert ht.classify(
        "fusion", line("jvp(ConvNetS2DT.fused_input_stage)/dot"), 0
    ) == "input-stage-fwd"
    assert ht.classify(
        "fusion", line("jvp(M)/conv1/conv"), 1 << 30
    ) == "conv1-fwd"
    assert ht.classify(
        "fusion", line("transpose(jvp(M))/conv2/conv"), 1 << 30
    ) == "conv2-dgrad"
    assert ht.classify(
        "fusion", line("transpose(jvp(M))/conv2/conv"), 1 << 20
    ) == "conv2-wgrad"
    assert ht.classify(
        "custom-call",
        line("jvp(M)/M._tail/bn9x/pallas_call",
             extra="tpu_custom_call "),
        0,
    ) == "pallas-kernel"
    assert ht.classify("copy", "  %c = bf16[1] copy(%a)", 0) \
        == "copy/transpose(no-provenance)"
