"""The sparse-tap conv1 kernel (ops/pallas_conv5_t.py) == the
scattered-3x3 path it replaces — fwd, stats, wgrad/dbias — plus the
scatter/gather index adjointness the VJP relies on. Interpret mode
(Mosaic lowering is pinned in tests/test_mosaic_lowering.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sandbox.models.convnet_s2d_t import space_to_depth_t
from tpu_sandbox.ops.pallas_conv5_t import (
    conv1_s2d_t,
    conv1_s2d_t_reference,
    conv1_s2d_t_stats,
    gather_dk5,
    scatter_k5,
)


def _case(n=2, hw=32, f1=8, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    img = jnp.asarray(rng.standard_normal((n, hw, hw)), dtype)
    x = space_to_depth_t(img, 4)
    k5 = jnp.asarray(0.3 * rng.standard_normal((5, 5, 1, f1)), dtype)
    b = jnp.asarray(rng.standard_normal(f1), dtype)
    return x, k5, b


def test_scatter_gather_adjoint():
    """<scatter(k), W> == <k, gather(W)> for random operands — the exact
    identity the custom VJP uses to route dW1 back to dk5."""
    rng = np.random.default_rng(3)
    k5 = jnp.asarray(rng.standard_normal((5, 5, 1, 8)), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((128, 64)), jnp.float32)
    lhs = float(jnp.vdot(scatter_k5(k5), w1))
    rhs = float(jnp.vdot(k5, gather_dk5(w1, 8)))
    np.testing.assert_allclose(lhs, rhs, rtol=1e-5)


def test_forward_matches_scattered_3x3():
    x, k5, b = _case()
    np.testing.assert_allclose(
        np.asarray(conv1_s2d_t(x, k5, b)),
        np.asarray(conv1_s2d_t_reference(x, k5, b)), atol=1e-5)


def test_stats_variant_matches():
    x, k5, b = _case(seed=1)
    y, s, ss = conv1_s2d_t_stats(x, k5, b)
    yr = conv1_s2d_t_reference(x, k5, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr), atol=1e-5)
    ya = np.asarray(yr, np.float32)
    np.testing.assert_allclose(np.asarray(s)[:, 0], ya.sum((0, 1, 3)),
                               atol=1e-3)
    np.testing.assert_allclose(np.asarray(ss)[:, 0],
                               (ya * ya).sum((0, 1, 3)), rtol=1e-5,
                               atol=1e-3)


def test_wgrad_matches_reference_grads():
    x, k5, b = _case(seed=2)
    gn = jax.grad(lambda k, b: jnp.sum(conv1_s2d_t(x, k, b) ** 2),
                  argnums=(0, 1))(k5, b)
    gr = jax.grad(
        lambda k, b: jnp.sum(conv1_s2d_t_reference(x, k, b) ** 2),
        argnums=(0, 1))(k5, b)
    for a, r, nm in zip(gn, gr, ("dk5", "db")):
        scale = float(jnp.max(jnp.abs(r)))
        assert float(jnp.max(jnp.abs(a - r))) / scale < 1e-6, nm


def test_image_edges_zero_padded():
    """SAME padding at the image boundary: a one-block-tall image forces
    every halo row through the zero-mask path."""
    x, k5, b = _case(n=1, hw=4, f1=4, seed=4)
    np.testing.assert_allclose(
        np.asarray(conv1_s2d_t(x, k5, b)),
        np.asarray(conv1_s2d_t_reference(x, k5, b)), atol=1e-5)


def test_differentiated_input_raises():
    """VERDICT r04 weak-5 / next-7: the zero-input-cotangent contract is
    GUARDED, not silent. Differentiating through the kernel's input
    (composing it after trainable preprocessing) must raise at trace
    time instead of producing silently-zero gradients; the data path
    (grad wrt weights only) stays allowed. The guard lives at the AD
    rule (custom_jvp + symbolic_zeros), so it fires across trace
    boundaries too — grad-of-jit and remat, where a tracer-type check
    at the wrapper would see only plain jaxpr tracers."""
    import pytest

    x, k5, b = _case()

    def loss_through_input(scale):
        # trainable preprocessing: x now depends on a differentiated value
        return jnp.sum(conv1_s2d_t(x * scale, k5, b))

    with pytest.raises(ValueError, match="ZERO input cotangent"):
        jax.grad(loss_through_input)(jnp.float32(1.0))

    # ...across a jit boundary (AD of the traced jaxpr, not of python)
    with pytest.raises(ValueError, match="ZERO input cotangent"):
        jax.grad(jax.jit(loss_through_input))(jnp.float32(1.0))

    # ...and under rematerialization
    with pytest.raises(ValueError, match="ZERO input cotangent"):
        jax.grad(jax.checkpoint(loss_through_input))(jnp.float32(1.0))

    # stats variant carries the same guard
    with pytest.raises(ValueError, match="ZERO input cotangent"):
        jax.grad(lambda s: jnp.sum(conv1_s2d_t_stats(x * s, k5, b)[0]))(
            jnp.float32(1.0))

    # the legitimate composition still differentiates (wrt weights, data x)
    g = jax.grad(lambda k: jnp.sum(conv1_s2d_t(x, k, b)))(k5)
    assert g.shape == k5.shape
    # ...including under jit (the production step is jitted)
    g2 = jax.jit(jax.grad(lambda k: jnp.sum(conv1_s2d_t(x, k, b))))(k5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g2), rtol=1e-6)


def test_wgrad_restage_variants_agree():
    """r05 wgrad restage: the explicit-gT native-dot variant and the
    Mosaic-auto lane-lane variant compute the SAME (dW1, db)."""
    from tpu_sandbox.ops.pallas_conv5_t import conv1_s2d_t_wgrad

    x, k5, b = _case(seed=5)
    g = jnp.asarray(
        np.random.default_rng(6).standard_normal(
            (x.shape[0], x.shape[1], 16 * k5.shape[-1], x.shape[3])),
        x.dtype)
    dw_gt, db_gt = conv1_s2d_t_wgrad(x, g, restage="gt")
    dw_auto, db_auto = conv1_s2d_t_wgrad(x, g, restage="auto")
    np.testing.assert_allclose(np.asarray(dw_gt), np.asarray(dw_auto),
                               rtol=1e-6, atol=1e-4)
    np.testing.assert_allclose(np.asarray(db_gt), np.asarray(db_auto),
                               rtol=1e-6, atol=1e-4)
