"""fused_bn_relu_pool_t == the transposed unfused chain, and == the NHWC
fused pair through layout transposes.

Pins the contract that lets ConvNetS2DT(fused_tail=True) swap the
transposed Pallas tail in (ops/pallas_bn_tail_t.py): identical pooled
output, batch stats, and gradients (y, gamma, beta), including the bf16
tie-splitting semantics, plus the ysums (conv-fused statistics) path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.ops.pallas_bn_tail import (
    fused_bn_relu_pool,
    unfused_reference as ref_chain_nhwc,
)
from tpu_sandbox.ops.pallas_bn_tail_t import (
    fused_bn_relu_pool_t,
    unfused_reference_t as ref_chain,
)


def _data(blk, co, hw, dtype=jnp.float32, seed=0, n=2):
    rng = np.random.default_rng(seed)
    c = blk * blk * co
    y = jnp.asarray(rng.standard_normal((n, hw, c, hw)), dtype)
    gamma = jnp.asarray(1 + 0.1 * rng.standard_normal(co), jnp.float32)
    beta = jnp.asarray(rng.standard_normal(co), jnp.float32)
    return y, gamma, beta


@pytest.mark.parametrize("blk,co,hw", [(4, 4, 12), (2, 16, 8), (4, 16, 8)])
def test_forward_matches_unfused(blk, co, hw):
    y, gamma, beta = _data(blk, co, hw)
    out, mu, var = fused_bn_relu_pool_t(y, gamma, beta, co, blk)
    ref, mu_r, var_r = ref_chain(y, gamma, beta, co, blk)
    np.testing.assert_allclose(np.asarray(mu), np.asarray(mu_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), np.asarray(var_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_matches_nhwc_pair_through_transpose():
    blk, co, hw = 4, 4, 8
    y, gamma, beta = _data(blk, co, hw, seed=3)
    out_t, mu_t, var_t = fused_bn_relu_pool_t(y, gamma, beta, co, blk)
    out_n, mu_n, var_n = fused_bn_relu_pool(
        y.transpose(0, 1, 3, 2), gamma, beta, co, blk)
    np.testing.assert_allclose(np.asarray(mu_t), np.asarray(mu_n), atol=1e-6)
    np.testing.assert_allclose(np.asarray(var_t), np.asarray(var_n),
                               atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(out_t), np.asarray(out_n.transpose(0, 1, 3, 2)),
        atol=1e-5)


@pytest.mark.parametrize("blk,co", [(4, 4), (2, 16)])
def test_gradients_match_unfused(blk, co):
    y, gamma, beta = _data(blk, co, 8, seed=1)
    rng = np.random.default_rng(11)
    cot = jnp.asarray(
        rng.standard_normal((2, 8, (blk // 2) ** 2 * co, 8)), jnp.float32
    )

    def loss_fused(y, gamma, beta):
        out, _, _ = fused_bn_relu_pool_t(y, gamma, beta, co, blk)
        return jnp.sum(out * cot)

    def loss_ref(y, gamma, beta):
        out, _, _ = ref_chain(y, gamma, beta, co, blk)
        return jnp.sum(out * cot)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(y, gamma, beta)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(y, gamma, beta)
    for name, a, b in zip(("dy", "dgamma", "dbeta"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, err_msg=name
        )


def test_bf16_tie_gradients_match_unfused():
    """bf16 rounding creates exact pool ties; the transposed kernel must
    split tied cotangents 0.5/0.5 on rounded values like the NHWC pair."""
    rng = np.random.default_rng(7)
    co, blk = 8, 2
    c = blk * blk * co
    y = jnp.asarray(
        np.round(rng.standard_normal((2, 4, c, 4)) * 4) / 4, jnp.bfloat16
    )
    gamma = jnp.ones(co, jnp.float32)
    beta = jnp.zeros(co, jnp.float32)
    cot = jnp.asarray(rng.standard_normal((2, 4, co, 4)), jnp.float32)

    def loss(f):
        def run(y):
            out, _, _ = f(y, gamma, beta, co, blk)
            return jnp.sum(out.astype(jnp.float32) * cot)
        return run

    gf = jax.grad(loss(fused_bn_relu_pool_t))(y)
    gr = jax.grad(loss(ref_chain))(y)
    np.testing.assert_allclose(
        np.asarray(gf, np.float32), np.asarray(gr, np.float32),
        atol=2e-2,
    )


def test_ysums_path_matches_self_computed_stats():
    """Stats handed in from the conv kernel ([C,1] sums of the rounded
    output) produce the same mu/var/output/grads as the tail's own pass,
    and the ysums cotangents are zero by contract."""
    blk, co, hw = 2, 16, 8
    y, gamma, beta = _data(blk, co, hw, seed=4)
    yf = np.asarray(y, np.float32)
    s = jnp.asarray(yf.transpose(0, 1, 3, 2).reshape(-1, y.shape[2])
                    .sum(0)[:, None])
    ss = jnp.asarray((yf ** 2).transpose(0, 1, 3, 2)
                     .reshape(-1, y.shape[2]).sum(0)[:, None])
    out_a, mu_a, var_a = fused_bn_relu_pool_t(y, gamma, beta, co, blk)
    out_b, mu_b, var_b = fused_bn_relu_pool_t(
        y, gamma, beta, co, blk, 1e-5, None, (s, ss))
    np.testing.assert_allclose(np.asarray(mu_b), np.asarray(mu_a),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(var_b), np.asarray(var_a),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_a),
                               atol=1e-5)

    def loss(y, s, ss):
        out, _, _ = fused_bn_relu_pool_t(
            y, gamma, beta, co, blk, 1e-5, None, (s, ss))
        return jnp.sum(out)

    dy, ds, dss = jax.grad(loss, argnums=(0, 1, 2))(y, s, ss)
    assert float(jnp.abs(ds).max()) == 0.0
    assert float(jnp.abs(dss).max()) == 0.0
    dy_ref = jax.grad(
        lambda y: jnp.sum(fused_bn_relu_pool_t(y, gamma, beta, co, blk)[0])
    )(y)
    np.testing.assert_allclose(np.asarray(dy), np.asarray(dy_ref),
                               atol=2e-4)
