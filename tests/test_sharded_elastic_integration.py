"""End-to-end sharded-checkpoint recovery over real processes (CPU, world
size 2, ZeRO-1 optimizer sharding): the two cases the two-phase commit and
the integrity manifests exist for.

1. ``kill_during_commit``: rank 0 SIGKILLed INSIDE the commit window (shard
   claimed, manifest not yet renamed) → the step is torn, never sealed →
   the restarted generation resumes from the previous *sealed* manifest and
   the final params AND per-rank optimizer shards are bitwise-identical to
   an uninterrupted run.
2. ``corrupt_shard``: a sealed step's shard is scribbled (manifest intact,
   step still LOOKS committed) → restore catches the SHA-256 mismatch,
   quarantines the step, falls back to the previous sealed one.

Same spawn-2-jax.distributed-processes-per-generation cost as
test_elastic_integration.py, hence slow / out of tier-1; the protocol
itself is covered fast in test_sharded_checkpoint.py.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "mnist_distributed.py"

# 64 synthetic samples / (bs 4 x 2 ranks) = 8 steps per epoch, 16 total.
# momentum gives ZeRO real per-rank optimizer state to lose.
COMMON = [
    "--elastic", "-g", "2", "--epochs", "2", "--batch-size", "4",
    "--image-size", "28", "--synthetic-n", "64", "--limit-steps", "8",
    "--dtype", "fp32", "--plan", "plain", "--log-every", "1000",
    "--ckpt-every", "2", "--zero", "--opt", "momentum", "--ckpt-sharded",
]
TOTAL_STEPS = 16
WORLD = 2


def run_elastic(ckpt_dir, fault_plan=None, timeout=600, extra=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_SANDBOX_BACKOFF"] = "0.1"
    env["TPU_SANDBOX_TERM_TIMEOUT"] = "10"
    if fault_plan is not None:
        env["TPU_SANDBOX_FAULT_PLAN"] = json.dumps(fault_plan)
    cmd = [sys.executable, str(SCRIPT), *COMMON, *extra,
           "--ckpt-dir", str(ckpt_dir)]
    return subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


def final_shards(ckpt_dir):
    """Every leaf of every rank's shard of the final sealed step — params
    (rank 0, replicated) AND each rank's own optimizer-state block."""
    sd = Path(ckpt_dir) / f"step-{TOTAL_STEPS:08d}"
    assert (sd / "MANIFEST.json").exists(), f"final step not sealed in {sd}"
    out = {}
    for r in range(WORLD):
        with np.load(sd / f"shard-{r:05d}.npz", allow_pickle=False) as z:
            for k in z.files:
                if k.startswith("leaf:"):
                    out[(r, k)] = z[k].copy()
    return out


def assert_bitwise_same(a, b):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=str(k))


def test_kill_during_commit_resumes_from_last_sealed_manifest(tmp_path):
    ref_dir = tmp_path / "ref"
    r = run_elastic(ref_dir)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 generation(s)" in r.stdout

    # rank 0 dies INSIDE step 4's commit window: its shard is written and
    # claimed but the manifest rename never happens → step 4 is torn
    crash_dir = tmp_path / "crash"
    r = run_elastic(
        crash_dir,
        fault_plan=[{"rank": 0, "step": 4, "action": "kill_during_commit"}],
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert "gen1:failure" in out and "gen2:ok" in out, out
    # step 4 never sealed → generation 2 resumes from sealed step 2, and
    # the torn step-4 debris is quarantined, not restored from
    assert "resumed from step 2" in out, out
    q = crash_dir.parent / (crash_dir.name + ".quarantine")
    assert any(p.name.startswith("step-00000004") for p in q.iterdir()), (
        list(q.iterdir()) if q.is_dir() else "no quarantine dir"
    )

    assert_bitwise_same(final_shards(ref_dir), final_shards(crash_dir))


def test_corrupt_sealed_shard_detected_and_fallen_past(tmp_path):
    ref_dir = tmp_path / "ref"
    r = run_elastic(ref_dir)
    assert r.returncode == 0, r.stdout + r.stderr

    # rank 0's maybe_fire(6) runs right AFTER it sealed step 6 (its save
    # blocks on the full two-phase commit), so the corruption hits a step
    # the manifest vouches for — then the kill forces a restart that must
    # see through the lie
    rot_dir = tmp_path / "rot"
    r = run_elastic(
        rot_dir,
        fault_plan=[
            {"rank": 0, "step": 6, "action": "corrupt_shard",
             "target": str(rot_dir)},
            {"rank": 0, "step": 6, "action": "kill"},
        ],
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert "gen1:failure" in out and "gen2:ok" in out, out
    # sealed-but-corrupt step 6 fails its SHA-256 check → quarantined →
    # fall back to sealed step 4
    assert "resumed from step 4" in out, out
    q = rot_dir.parent / (rot_dir.name + ".quarantine")
    assert any(p.name.startswith("step-00000006") for p in q.iterdir()), (
        list(q.iterdir()) if q.is_dir() else "no quarantine dir"
    )

    assert_bitwise_same(final_shards(ref_dir), final_shards(rot_dir))

    # the offline auditor agrees the surviving directory is clean
    sys.path.insert(0, str(REPO))
    from tools.verify_ckpt import main as verify_main

    assert verify_main([str(rot_dir)]) == 0


def test_grad_compress_residual_survives_crash(tmp_path):
    """--grad-compress int8 under the same kill_during_commit fault: the
    error-feedback residual is real training state (dropping it on
    resume would re-inject stale quantization error), so it rides the
    sharded checkpoint as a per-rank leaf and the crashed run's final
    shards — residual included — are bitwise-identical to an
    uninterrupted run's."""
    extra = ("--grad-compress", "int8")
    ref_dir = tmp_path / "ref"
    r = run_elastic(ref_dir, extra=extra)
    assert r.returncode == 0, r.stdout + r.stderr

    ref = final_shards(ref_dir)
    res_keys = [k for k in ref if "grad_residual" in k[1]]
    assert res_keys, sorted(k[1] for k in ref)
    # both ranks checkpoint their own residual, and it is nonzero (the
    # quantizer always drops SOMETHING on real gradients)
    assert {k[0] for k in res_keys} == set(range(WORLD))
    assert any(np.abs(ref[k]).max() > 0 for k in res_keys)

    crash_dir = tmp_path / "crash"
    r = run_elastic(
        crash_dir,
        fault_plan=[{"rank": 0, "step": 4, "action": "kill_during_commit"}],
        extra=extra,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "gen1:failure" in r.stdout and "gen2:ok" in r.stdout, r.stdout
    assert "resumed from step 2" in r.stdout, r.stdout

    assert_bitwise_same(ref, final_shards(crash_dir))
