"""Collectives tests — upgrade of the reference's eyeball verification
(allreduce_toy.py prints sums for humans; SURVEY §4) into assertions:
psum of known values == analytic sum, etc., on 8 virtual devices."""

import numpy as np
import pytest

from tpu_sandbox.parallel.collectives import CollectiveGroup, sub_groups, world_group
from tpu_sandbox.runtime.mesh import make_mesh


@pytest.fixture(scope="module")
def group():
    return world_group()


def test_all_reduce_sum_matches_analytic(group):
    vals = np.arange(8.0)
    out = np.asarray(group.all_reduce(vals, "sum"))
    np.testing.assert_allclose(out, np.full(8, vals.sum()))


def test_all_reduce_ops(group):
    vals = np.array([3.0, 1.0, 4.0, 1.0, 5.0, 9.0, 2.0, 6.0])
    np.testing.assert_allclose(np.asarray(group.all_reduce(vals, "mean")), np.full(8, vals.mean()))
    np.testing.assert_allclose(np.asarray(group.all_reduce(vals, "max")), np.full(8, 9.0))
    np.testing.assert_allclose(np.asarray(group.all_reduce(vals, "min")), np.full(8, 1.0))
    with pytest.raises(ValueError, match="op"):
        group.all_reduce(vals, "xor")


def test_all_reduce_multidim(group):
    vals = np.arange(16.0).reshape(8, 2)
    out = np.asarray(group.all_reduce(vals))
    np.testing.assert_allclose(out, np.tile(vals.sum(0), (8, 1)))


def test_all_gather(group):
    vals = np.arange(8.0) * 10
    out = np.asarray(group.all_gather(vals))
    np.testing.assert_allclose(out, vals)  # replicated full copy


def test_reduce_scatter(group):
    # each rank contributes the payload [0..15]; rank i gets slice i of the
    # elementwise sum (8x the payload), 2 elements per rank.
    payload = np.arange(16.0)
    vals = np.tile(payload, (8, 1))
    out = np.asarray(group.reduce_scatter(vals))
    np.testing.assert_allclose(out, (payload * 8).reshape(8, 2))
    with pytest.raises(ValueError, match="reduce_scatter"):
        group.reduce_scatter(np.ones((8, 3)))


def test_broadcast(group):
    vals = np.arange(8.0)
    out = np.asarray(group.broadcast(vals, root=3))
    np.testing.assert_allclose(out, 3.0)
    out0 = np.asarray(group.broadcast(vals))
    np.testing.assert_allclose(out0, 0.0)


def test_shift_ring(group):
    vals = np.arange(8.0)
    out = np.asarray(group.shift(vals, 1))
    np.testing.assert_allclose(out, np.roll(vals, 1))
    back = np.asarray(group.shift(vals, -1))
    np.testing.assert_allclose(back, np.roll(vals, -1))


def test_barrier_completes(group):
    group.barrier()  # must not deadlock or raise


def test_subgroup_reduce_on_multiaxis_mesh():
    # 2x4 mesh: reducing over 'model' must keep 'data' rows independent —
    # the once-created analogue of dist.new_group(range(gpus)).
    mesh = make_mesh({"data": 2, "model": 4})
    g = sub_groups(mesh, "model")
    assert g.size == 4
    vals = np.arange(4.0)
    out = np.asarray(g.all_reduce(vals))
    np.testing.assert_allclose(out, np.full(4, 6.0))


def test_group_axis_validation():
    mesh = make_mesh({"data": 2, "model": 4})
    with pytest.raises(ValueError, match="pass axis"):
        CollectiveGroup(mesh)
    with pytest.raises(ValueError, match="not in mesh"):
        CollectiveGroup(mesh, "expert")


def test_put_validates_leading_dim(group):
    with pytest.raises(ValueError, match="divisible"):
        group.put(np.ones(3))


def test_bandwidth_bench_runs(group):
    r = group.allreduce_bandwidth(nbytes=1 << 16, iters=8)
    assert r["bytes"] == (1 << 16)
    # noise can zero the differential on a loaded CPU host; a published
    # number must be positive, a degraded line must say why
    assert r["busbw_GBps"] > 0 or "degraded" in r


def test_all_to_all_transpose(mesh8):
    from tpu_sandbox.parallel import CollectiveGroup

    g = CollectiveGroup(mesh8, "data")
    # rank i holds block [i]; element [i, j] must land at [j, i]
    vals = np.arange(64, dtype=np.float32).reshape(8, 8, 1)
    out = np.asarray(g.all_to_all(vals))
    np.testing.assert_array_equal(out, vals.transpose(1, 0, 2))


def test_all_to_all_rejects_bad_shape(mesh8):
    from tpu_sandbox.parallel import CollectiveGroup

    g = CollectiveGroup(mesh8, "data")
    with pytest.raises(ValueError, match="all_to_all wants"):
        g.all_to_all(np.zeros((8, 3)))
