"""Compressed gradient synchronization (parallel/collectives.py
CompressedAllReduce + the engine wiring in parallel/data_parallel.py and
parallel/pjit_engine.py).

The correctness bar, per mode:
  - 'none' must be BYTE-IDENTICAL to the pre-compression path — the
    policy is pure dispatch, the original lax.pmean/psum_scatter lines
    are untouched, and TrainState gains only an empty pytree slot;
  - 'bf16' tracks fp32 to cast precision;
  - 'int8' + error feedback must CONVERGE like fp32 (the acceptance
    criterion: final loss within 5e-2 relative over >= 50 steps, and a
    strictly better trajectory than int8 without feedback) — per-step
    closeness is NOT the claim, telescoped-error closeness is;
  - the traffic accounting (analytic + HLO-derived) must show the 2x /
    ~4x payload reductions the modes exist for.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_sandbox.data import synthetic_mnist
from tpu_sandbox.data.mnist import normalize
from tpu_sandbox.models import ConvNet
from tpu_sandbox.parallel import CompressedAllReduce, DataParallel, PjitEngine
from tpu_sandbox.parallel.collectives import as_compress_policy, world_group
from tpu_sandbox.runtime.mesh import make_mesh
from tpu_sandbox.train import TrainState
from tpu_sandbox.train.checkpoint import ShardedCheckpoint

WORLD = 8


def setup(lr=0.05, momentum=0.0, use_bn=False):
    model = ConvNet(use_bn=use_bn)
    tx = optax.sgd(lr, momentum=momentum) if momentum else optax.sgd(lr)
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx)
    images, labels = synthetic_mnist(n=16, seed=0)
    return model, tx, state, normalize(images), labels.astype("int32")


# -- policy object ----------------------------------------------------------


def test_policy_validation():
    with pytest.raises(ValueError, match="not in"):
        CompressedAllReduce(mode="fp4")
    with pytest.raises(ValueError, match="block"):
        CompressedAllReduce(mode="int8", block=0)
    assert as_compress_policy(None).mode == "none"
    assert as_compress_policy("bf16").mode == "bf16"
    p = CompressedAllReduce(mode="int8")
    assert as_compress_policy(p) is p
    assert p.needs_residual
    assert not CompressedAllReduce(
        mode="int8", error_feedback=False).needs_residual
    assert not CompressedAllReduce(mode="bf16").needs_residual


def test_wire_bytes_accounting():
    """Analytic wire accounting: exact values for an evenly-divisible
    leaf, and the headline ratios at a production-sized leaf where block
    padding is negligible."""
    n = 2048  # divides WORLD * block exactly: no padding term
    none = CompressedAllReduce().wire_bytes([n], WORLD)
    bf16 = CompressedAllReduce(mode="bf16").wire_bytes([n], WORLD)
    int8 = CompressedAllReduce(mode="int8").wire_bytes([n], WORLD)
    assert none == {"total": 4 * n, "payload": 4 * n, "overhead": 0}
    assert bf16 == {"total": 2 * n, "payload": 2 * n, "overhead": 0}
    # chunk = 256, nb = 1: shot1 = 8*256 q + 8*4 scales, shot2 = 256 + 4
    assert int8["total"] == 8 * 256 + 8 * 4 + 256 + 4
    assert int8["payload"] == n + n // WORLD
    assert int8["overhead"] == int8["total"] - int8["payload"]

    big = 1 << 20
    est = CompressedAllReduce(mode="int8").wire_bytes([big], WORLD)
    # all-in wire ratio approaches 4x as padding/scales amortize; the
    # payload ratio is exactly 4 / (1 + 1/WORLD) = 3.56x at WORLD=8
    assert 4 * big / est["total"] > 3.4
    assert 4 * big / est["payload"] == pytest.approx(
        4 / (1 + 1 / WORLD), rel=1e-3)
    # bf16 is exactly half of fp32 whatever the leaf set
    sizes = [400, 16, 12800, 32, 15680, 10]
    assert (CompressedAllReduce(mode="bf16").wire_bytes(sizes, WORLD)["total"]
            * 2 == CompressedAllReduce().wire_bytes(sizes, WORLD)["total"])


# -- the quantized collective itself ----------------------------------------


def test_int8_block_pmean_error_bound(mesh8):
    """The compressed mean tracks the exact mean within the quantizer's
    per-block bound: |err| <= mean of block absmax / 127 per shot."""
    group = world_group(mesh8)
    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.standard_normal((WORLD, 33, 77)), jnp.float32)
    exact = np.asarray(jnp.mean(vals, axis=0))
    policy = CompressedAllReduce(mode="int8", block=256,
                                 error_feedback=False)
    out = np.asarray(group.compressed_all_reduce(vals, policy))
    assert out.shape == vals.shape
    for r in range(1, WORLD):  # every rank computes the SAME mean
        np.testing.assert_array_equal(out[0], out[r])
    # two quantizations of ~N(0,1) data: a couple absmax/127 steps
    bound = 2.5 * float(np.abs(vals).max()) / 127.0
    assert float(np.abs(out[0] - exact).max()) < bound


def test_int8_error_feedback_telescopes(mesh8):
    """Sum over steps of (compressed mean) + final residual/WORLD ==
    sum of exact means, to fp32 roundoff: the residual carries exactly
    what the quantizer dropped, so the error telescopes instead of
    accumulating — the whole reason error feedback exists."""
    from tpu_sandbox.utils.compat import shard_map

    policy = CompressedAllReduce(mode="int8", block=128)
    rng = np.random.default_rng(1)
    steps = [jnp.asarray(rng.standard_normal((WORLD, 19, 53)), jnp.float32)
             for _ in range(5)]

    def body(v, res):
        return policy.pmean(v[0], "data", WORLD, res[0])

    run = shard_map(
        lambda v, r: tuple(x[None] for x in body(v, r)),
        mesh=mesh8, in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data")), check_vma=False)

    res = jnp.zeros((WORLD, 19, 53), jnp.float32)
    got = np.zeros((19, 53), np.float64)
    want = np.zeros((19, 53), np.float64)
    for v in steps:
        mean, res = run(v, res)
        got += np.asarray(mean[0], np.float64)
        want += np.asarray(jnp.mean(v, axis=0), np.float64)
    # the residual's cross-rank sum is what is still owed to the mean
    got += np.asarray(jnp.sum(res, axis=0), np.float64) / WORLD
    np.testing.assert_allclose(got, want, atol=1e-5)


# -- DataParallel wiring ----------------------------------------------------


def _run_steps(dp, state, images, labels, n_steps):
    dstate = dp.shard_state(state)
    di, dl = dp.shard_batch(images, labels)
    losses = []
    for _ in range(n_steps):
        dstate, loss = dp.train_step(dstate, di, dl)
        losses.append(float(jnp.mean(loss)))
    return dstate, losses


def test_none_mode_bitwise_identical(mesh8):
    """grad_compress='none' (and the default ctor) is byte-for-byte the
    pre-compression engine: same params after 3 steps, and no residual
    state is materialized."""
    model, tx, state, images, labels = setup(momentum=0.9)
    base = DataParallel(model, tx, mesh8, donate=False)
    comp = DataParallel(model, tx, mesh8, donate=False, grad_compress="none")
    assert base.compress == comp.compress == CompressedAllReduce()
    s_base, l_base = _run_steps(base, state, images, labels, 3)
    s_comp, l_comp = _run_steps(comp, state, images, labels, 3)
    assert l_base == l_comp
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        s_base.params, s_comp.params)
    assert s_comp.grad_residual is None
    assert jax.tree.leaves(s_comp.grad_residual) == []


def test_bf16_mode_tracks_fp32(mesh8):
    model, tx, state, images, labels = setup()
    ref = DataParallel(model, tx, mesh8, donate=False)
    bf = DataParallel(model, tx, mesh8, donate=False, grad_compress="bf16")
    s_ref, l_ref = _run_steps(ref, state, images, labels, 3)
    s_bf, l_bf = _run_steps(bf, state, images, labels, 3)
    assert s_bf.grad_residual is None  # bf16 is stateless
    np.testing.assert_allclose(l_bf, l_ref, rtol=2e-2)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-3), s_bf.params,
        s_ref.params)


@pytest.mark.parametrize(
    "block",
    [256,
     # the large-block twin re-proves the margin-grows-with-block-size
     # corollary; one full 3x55-step convergence run is enough for tier-1
     pytest.param(4096, marks=pytest.mark.slow)])
def test_int8_ef_convergence_tracks_fp32(mesh8, block):
    """THE acceptance criterion: over >= 50 steps (momentum SGD, the
    reference's training config), int8 + error feedback lands on the
    fp32 final loss (5e-2 relative, abs floor 1e-3 since all runs
    converge to ~1e-7 from an initial ~2.3) AND tracks the fp32 loss
    trajectory strictly better than int8 without feedback — 2.3x /
    3.1x mean-deviation margins at these seeds, growing with block
    size exactly as the error-feedback theory predicts. (In plateau
    regimes where quantization error is below trajectory noise the
    ordering is a coin flip — the claim is about the converging
    regime, which is what this pins.)"""
    model, tx, state, images, labels = setup(momentum=0.9)
    n_steps = 55
    _, l_fp32 = _run_steps(
        DataParallel(model, tx, mesh8, donate=False),
        state, images, labels, n_steps)
    s_ef, l_ef = _run_steps(
        DataParallel(model, tx, mesh8, donate=False,
                     grad_compress=CompressedAllReduce(
                         mode="int8", block=block)),
        state, images, labels, n_steps)
    _, l_raw = _run_steps(
        DataParallel(model, tx, mesh8, donate=False,
                     grad_compress=CompressedAllReduce(
                         mode="int8", block=block, error_feedback=False)),
        state, images, labels, n_steps)

    assert abs(l_ef[-1] - l_fp32[-1]) <= max(5e-2 * l_fp32[-1], 1e-3)
    dev_ef = float(np.mean(np.abs(np.array(l_ef) - np.array(l_fp32))))
    dev_raw = float(np.mean(np.abs(np.array(l_raw) - np.array(l_fp32))))
    assert dev_ef < dev_raw, (dev_ef, dev_raw)
    # the residual exists, is per-rank, and is doing real work
    res_leaves = jax.tree.leaves(s_ef.grad_residual)
    assert res_leaves and all(r.shape[0] == WORLD for r in res_leaves)
    assert any(float(jnp.abs(r).max()) > 0 for r in res_leaves)


def test_zero_composes_with_int8(mesh8):
    """ZeRO-1 + int8 takes the full compressed mean then slices each
    rank's block — elementwise update math, so it must match plain DP
    with the same compression to fp reassociation."""
    model, tx, state, images, labels = setup(momentum=0.9)
    s_plain, l_plain = _run_steps(
        DataParallel(model, tx, mesh8, donate=False, grad_compress="int8"),
        state, images, labels, 4)
    s_zero, l_zero = _run_steps(
        DataParallel(model, tx, mesh8, donate=False, grad_compress="int8",
                     zero=True),
        state, images, labels, 4)
    np.testing.assert_allclose(l_zero, l_plain, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        s_zero.params, s_plain.params)


def test_residual_checkpoint_round_trip(mesh8, tmp_path):
    """Crash-resume equivalence in-process: 2 steps -> sharded save
    (residual rides as a 'shard0' leaf) -> restore through the
    checkpoint_template slot -> 2 more steps == 4 uninterrupted steps,
    bitwise, residual included."""
    model, tx, state, images, labels = setup(momentum=0.9)
    dp = DataParallel(model, tx, mesh8, donate=False, grad_compress="int8")
    di, dl = dp.shard_batch(images, labels)

    dstate = dp.shard_state(state)
    for _ in range(4):
        dstate, _ = dp.train_step(dstate, di, dl)
    ref = dstate  # uninterrupted 4 steps

    dstate = dp.shard_state(state)
    for _ in range(2):
        dstate, _ = dp.train_step(dstate, di, dl)
    spec = dp.checkpoint_spec(dstate)
    assert all(
        s == "shard0"
        for s in jax.tree.leaves(spec.grad_residual))
    ck = ShardedCheckpoint(tmp_path / "ck", rank=0, world_size=1,
                           verbose=False, commit_timeout=5.0)
    assert ck.save(dstate.host_view(), spec, 2, epoch=0, offset=0)

    template = dp.checkpoint_template(
        TrainState.create(model, jax.random.key(0),
                          jnp.zeros((1, 28, 28, 1)), tx))
    restored, meta = ck.restore(template)
    assert meta["step"] == 2
    resumed = dp.shard_state(restored, stats_expanded=True)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        resumed.grad_residual, dstate.grad_residual)
    for _ in range(2):
        resumed, _ = dp.train_step(resumed, di, dl)
    for name in ("params", "opt_state", "grad_residual"):
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            getattr(resumed, name), getattr(ref, name))


def test_template_without_residual_slot_would_drop_it(mesh8):
    """checkpoint_template is what guards against the silent-drop
    failure mode: it attaches the residual slot iff the policy needs
    one, and is a no-op otherwise."""
    model, tx, state, _, _ = setup()
    dp_none = DataParallel(model, tx, mesh8, donate=False)
    assert dp_none.checkpoint_template(state).grad_residual is None
    dp = DataParallel(model, tx, mesh8, donate=False, grad_compress="int8")
    t = dp.checkpoint_template(state)
    jax.tree.map(
        lambda r, p: (r.shape == np.shape(p)
                      and float(np.abs(r).max()) == 0.0),
        t.grad_residual, t.params)
    # idempotent: a template that already has the slot is left alone
    assert dp.checkpoint_template(t) is t


# -- traffic accounting against the compiled artifact -----------------------


def test_hlo_collective_bytes_drop_under_int8(mesh8):
    """The compiled SPMD step's cross-replica collective operand bytes:
    int8 swaps the fp32 all-reduce for an int8 all_to_all + all_gather
    and must land well under the fp32 bytes. (bf16 is asserted on the
    analytic path only — XLA:CPU upcasts the bf16 all-reduce operand to
    f32, so its HLO bytes are a CPU artifact.)"""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    from hlo_traffic import collective_bytes

    model, tx, state, images, labels = setup(momentum=0.9)
    got = {}
    for mode in ("none", "int8"):
        dp = DataParallel(model, tx, mesh8, donate=False,
                          grad_compress=mode)
        dstate = dp.shard_state(state)
        text = dp.lower_step(
            dstate, *dp.shard_batch(images, labels)).compile().as_text()
        got[mode] = collective_bytes(text)
    assert got["none"]["by_opcode"].keys() == {"all-reduce"}
    assert {"all-to-all", "all-gather"} <= got["int8"]["by_opcode"].keys()
    assert "all-reduce" not in got["int8"]["by_opcode"]
    # ~2.6x on this deliberately tiny model (block padding dominates its
    # small leaves); the analytic path in test_wire_bytes_accounting
    # pins the asymptotic ~4x
    assert got["int8"]["total"] < 0.45 * got["none"]["total"]


# -- PjitEngine wiring ------------------------------------------------------


def test_pjit_engine_compressed_modes(mesh8):
    model, tx, state, images, labels = setup()
    ref = PjitEngine(model, tx, mesh8, donate=False)
    sstate = ref.shard_state(state)
    _, l_ref = ref.train_step(sstate, *ref.shard_batch(images, labels))
    for mode, rtol in (("none", 0.0), ("bf16", 2e-2), ("int8", 2e-2)):
        eng = PjitEngine(model, tx, mesh8, donate=False, grad_compress=mode)
        sstate = eng.shard_state(state)
        _, loss = eng.train_step(sstate, *eng.shard_batch(images, labels))
        if mode == "none":
            assert float(loss) == float(l_ref)
        else:
            np.testing.assert_allclose(float(loss), float(l_ref), rtol=rtol)


def test_pjit_engine_compression_restrictions(mesh8):
    """The pjit path's compression is deliberately restricted to its
    plain-DP configuration; every unsupported combination fails loud at
    construction or first build, never silently uncompressed."""
    model, tx, state, images, labels = setup()
    with pytest.raises(ValueError, match="rules"):
        PjitEngine(model, tx, mesh8, donate=False, grad_compress="int8",
                   rules=[("fc/kernel", P(None, "model"))])
    mesh2 = make_mesh({"data": 4, "fsdp": 2})
    with pytest.raises(ValueError, match="fsdp"):
        PjitEngine(model, tx, mesh2, donate=False, grad_compress="bf16",
                   fsdp_axis="fsdp")
    bn_model = ConvNet(use_bn=True)
    bn_state = TrainState.create(
        bn_model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), optax.sgd(0.05))
    eng = PjitEngine(bn_model, optax.sgd(0.05), mesh8, donate=False,
                     grad_compress="int8")
    with pytest.raises(ValueError, match="batch"):
        sstate = eng.shard_state(bn_state)
        eng.train_step(sstate, *eng.shard_batch(images, labels))
