"""Overlapped step pipeline (parallel/buckets.py + data/loader.py
PrefetchLoader + tools/hlo_schedule.py).

The correctness bar:
  - bucket planning is a pure, total function of (sizes, target, dtypes);
  - the bucketed sync with overlap ON and grad_compress='none' is
    BITWISE the monolithic engine — bucketing reorders collectives, never
    values (and with overlap off the code path is literally the old one);
  - int8 + per-bucket error feedback still converges like fp32 (the PR-3
    acceptance bound, now with bucket-local residual blocks);
  - the prefetch loader yields exactly the wrapped loader's stream, in
    order, under crash/resume — elastic parity must not depend on whether
    the input pipeline is threaded;
  - schedule_report() reads a canned scheduled-HLO fixture correctly
    (the real chipless v5e receipt is tools/hlo_schedule.py's job).
"""

import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_sandbox.data import synthetic_mnist
from tpu_sandbox.data.loader import BatchLoader, PrefetchLoader
from tpu_sandbox.data.mnist import normalize
from tpu_sandbox.models import ConvNet
from tpu_sandbox.parallel import (
    CompressedAllReduce,
    DataParallel,
    PjitEngine,
    plan_buckets,
)
from tpu_sandbox.train import TrainState

WORLD = 8

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


def setup(lr=0.05, momentum=0.0):
    model = ConvNet(use_bn=False)
    tx = optax.sgd(lr, momentum=momentum) if momentum else optax.sgd(lr)
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx)
    images, labels = synthetic_mnist(n=16, seed=0)
    return model, tx, state, normalize(images), labels.astype("int32")


def _run_steps(dp, state, images, labels, n_steps):
    dstate = dp.shard_state(state)
    di, dl = dp.shard_batch(images, labels)
    losses = []
    for _ in range(n_steps):
        dstate, loss = dp.train_step(dstate, di, dl)
        losses.append(float(jnp.mean(loss)))
    return dstate, losses


# -- bucket planning --------------------------------------------------------


def test_plan_buckets_grouping():
    # consecutive greedy fill to the target
    assert plan_buckets([100] * 5, 250) == [(0, 2), (2, 4), (4, 5)]
    # a single over-target leaf still gets its own bucket
    assert plan_buckets([100, 1000, 100], 250) == [(0, 1), (1, 2), (2, 3)]
    # one giant bucket when everything fits
    assert plan_buckets([1, 2, 3], 1 << 20) == [(0, 3)]
    # a dtype-key change forces a boundary even under the target
    assert plan_buckets([4, 4, 4, 4], 1 << 20,
                        keys=["f32", "f32", "i32", "i32"]) == [(0, 2), (2, 4)]
    assert plan_buckets([], 100) == []


def test_plan_buckets_covers_every_leaf_once():
    rng = np.random.default_rng(0)
    sizes = [int(s) for s in rng.integers(1, 5000, size=40)]
    spans = plan_buckets(sizes, 4096)
    flat = [i for a, b in spans for i in range(a, b)]
    assert flat == list(range(len(sizes)))


def test_plan_buckets_validation():
    with pytest.raises(ValueError, match="positive"):
        plan_buckets([1, 2], 0)
    with pytest.raises(ValueError, match="length"):
        plan_buckets([1, 2], 100, keys=["f32"])


# -- DataParallel wiring ----------------------------------------------------


def test_overlap_none_bitwise_identical(mesh8):
    """Bucketed sync with 'none' compression is a plain pmean over each
    flat bucket — elementwise, so the whole training trajectory must be
    byte-for-byte the monolithic engine's. bucket_mb is sized so the
    ~116KB ConvNet grad really splits into several buckets."""
    model, tx, state, images, labels = setup(momentum=0.9)
    base = DataParallel(model, tx, mesh8, donate=False)
    over = DataParallel(model, tx, mesh8, donate=False,
                        overlap_grad_sync=True, bucket_mb=0.02)
    s_base, l_base = _run_steps(base, state, images, labels, 3)
    s_over, l_over = _run_steps(over, state, images, labels, 3)
    assert l_over == l_base
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        s_over.params, s_base.params)
    assert s_over.grad_residual is None


def test_overlap_int8_ef_convergence(mesh8):
    """PR-3's acceptance bound survives bucketing: int8 with PER-BUCKET
    error-feedback residuals lands on the fp32 final loss (5e-2 relative,
    1e-3 abs floor) over >= 50 momentum-SGD steps, and the residual still
    checkpoints leaf-shaped and per-rank."""
    model, tx, state, images, labels = setup(momentum=0.9)
    n_steps = 55
    _, l_fp32 = _run_steps(
        DataParallel(model, tx, mesh8, donate=False),
        state, images, labels, n_steps)
    s_ef, l_ef = _run_steps(
        DataParallel(model, tx, mesh8, donate=False, grad_compress="int8",
                     overlap_grad_sync=True, bucket_mb=0.02),
        state, images, labels, n_steps)
    assert abs(l_ef[-1] - l_fp32[-1]) <= max(5e-2 * l_fp32[-1], 1e-3)
    res_leaves = jax.tree.leaves(s_ef.grad_residual)
    params = jax.tree.leaves(s_ef.params)
    assert len(res_leaves) == len(params)
    # leaf-shaped (bucket concat/split is internal), per-rank expanded
    assert all(r.shape == (WORLD, *p.shape)
               for r, p in zip(res_leaves, params))
    assert any(float(jnp.abs(r).max()) > 0 for r in res_leaves)


def test_overlap_zero_composes(mesh8):
    """ZeRO-1 under the bucketed sync: full bucketed mean, then each rank
    slices its optimizer block — elementwise update math, so it matches
    plain bucketed DP to fp reassociation."""
    model, tx, state, images, labels = setup(momentum=0.9)
    s_plain, l_plain = _run_steps(
        DataParallel(model, tx, mesh8, donate=False,
                     overlap_grad_sync=True, bucket_mb=0.02),
        state, images, labels, 4)
    s_zero, l_zero = _run_steps(
        DataParallel(model, tx, mesh8, donate=False,
                     overlap_grad_sync=True, bucket_mb=0.02, zero=True),
        state, images, labels, 4)
    np.testing.assert_allclose(l_zero, l_plain, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        s_zero.params, s_plain.params)


def test_bucketed_hlo_splits_the_collective(mesh8):
    """The compiled step carries one all-reduce PER BUCKET (the barrier
    chain in sync_buckets keeps the combiner from re-merging them);
    ~116KB of ConvNet grads at a 0.02MB target is 4 buckets."""
    from hlo_schedule import build_overlapped_hlo, schedule_report

    devs = np.array(jax.devices()[:WORLD])
    bucketed = schedule_report(build_overlapped_hlo(devs, bucket_mb=0.02))
    mono = schedule_report(build_overlapped_hlo(devs, overlap=False))
    assert bucketed["collective_count"] == 4
    # the monolithic path syncs per leaf (6 ConvNet grads; XLA:CPU runs no
    # combiner) — on TPU the combiner merges those into ONE all-reduce,
    # which is exactly what the barrier chain stops it doing to buckets
    assert mono["collective_count"] == 6
    # same payload either way: bucketing splits bytes, never adds any
    assert bucketed["comm_bytes_total"] == mono["comm_bytes_total"]


def test_engine_validation(mesh8):
    model, tx, state, images, labels = setup()
    with pytest.raises(ValueError, match="bucket_mb"):
        DataParallel(model, tx, mesh8, donate=False, bucket_mb=0.0)
    with pytest.raises(ValueError, match="bucket_mb"):
        PjitEngine(model, tx, mesh8, donate=False, bucket_mb=-1)
    # overlap inherits the compressed path's pure-DP restriction
    with pytest.raises(ValueError, match="overlap_grad_sync"):
        PjitEngine(model, tx, mesh8, donate=False, overlap_grad_sync=True,
                   rules=[("fc/kernel", P(None, "model"))])


def test_pjit_engine_overlap_matches(mesh8):
    model, tx, state, images, labels = setup()
    ref = PjitEngine(model, tx, mesh8, donate=False)
    sstate = ref.shard_state(state)
    _, l_ref = ref.train_step(sstate, *ref.shard_batch(images, labels))
    eng = PjitEngine(model, tx, mesh8, donate=False,
                     overlap_grad_sync=True, bucket_mb=0.02)
    sstate = eng.shard_state(state)
    _, loss = eng.train_step(sstate, *eng.shard_batch(images, labels))
    assert float(loss) == float(l_ref)


# -- prefetch loader --------------------------------------------------------


def _loader_stream(loader, epochs):
    out = []
    for e in range(epochs):
        loader.set_epoch(e)
        out.extend((x.copy(), y.copy()) for x, y in loader)
    return out


def test_prefetch_stream_identical_to_wrapped_loader():
    images, labels = synthetic_mnist(n=30, seed=1)
    mk = lambda: BatchLoader(images, labels, 8, shuffle=True, seed=3)
    sync = _loader_stream(mk(), epochs=2)
    pre = _loader_stream(PrefetchLoader(mk()), epochs=2)
    assert len(pre) == len(sync)
    for (xa, ya), (xb, yb) in zip(pre, sync):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
    assert len(PrefetchLoader(mk())) == len(mk())


def test_prefetch_stage_runs_in_producer():
    images, labels = synthetic_mnist(n=8, seed=0)
    seen_threads = []

    def stage(x, y):
        seen_threads.append(threading.current_thread().name)
        return x + 1.0, y

    pl = PrefetchLoader(BatchLoader(images, labels, 4), stage=stage)
    batches = list(pl)
    assert len(batches) == 2
    np.testing.assert_array_equal(batches[0][0], images[:4] + 1.0)
    assert set(seen_threads) == {"prefetch-loader"}


def test_prefetch_propagates_producer_error():
    class Exploding:
        def __iter__(self):
            yield (np.zeros(1), np.zeros(1))
            raise RuntimeError("disk on fire")

    it = iter(PrefetchLoader(Exploding()))
    next(it)
    with pytest.raises(RuntimeError, match="disk on fire"):
        next(it)


def test_prefetch_consumer_break_stops_producer():
    images, labels = synthetic_mnist(n=64, seed=0)
    pl = PrefetchLoader(BatchLoader(images, labels, 4), depth=2)
    for i, _ in enumerate(pl):
        if i == 1:
            break  # preemption raising out of the loop looks like this
    # the producer thread is joined by the generator's finally
    assert not [t for t in threading.enumerate()
                if t.name == "prefetch-loader" and t.is_alive()]
    with pytest.raises(ValueError, match="depth"):
        PrefetchLoader(BatchLoader(images, labels, 4), depth=0)


# -- prefetch x elastic resume ---------------------------------------------


class _Loader:
    def __init__(self, batches):
        self.batches = batches

    def set_epoch(self, epoch):
        pass

    def __len__(self):
        return len(self.batches)

    def __iter__(self):
        yield from self.batches


def _toy_batches(n_batches=8, bs=4, dim=3, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(dim,)).astype(np.float32)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(bs, dim)).astype(np.float32)
        out.append((x, (x @ w_true).astype(np.float32)))
    return out


@pytest.mark.parametrize("preempt_step", [3, 11])
def test_prefetch_elastic_resume_parity(tmp_path, preempt_step):
    """Kill mid-epoch WITH the prefetcher active, resume WITH the
    prefetcher: final weights bitwise equal to the synchronous
    uninterrupted run, and the applied-batch order identical — the
    (epoch, offset) metadata means the same thing threaded or not."""
    from tpu_sandbox.train.checkpoint import HostCheckpoint
    from tpu_sandbox.train.trainer import (
        Preempted,
        PreemptionHandler,
        train_resumable,
    )

    batches = _toy_batches()
    ids = {id(x): i for i, (x, _) in enumerate(batches)}

    def make_step(seq):
        @jax.jit
        def sgd(state, x, y):
            loss, g = jax.value_and_grad(
                lambda w: jnp.mean((x @ w - y) ** 2))(state["w"])
            return {"w": state["w"] - 0.05 * g}, loss

        def step(state, x, y):
            seq.append(ids[id(x)])
            return sgd(state, x, y)

        return step

    fresh = lambda: {"w": jnp.zeros(3, jnp.float32)}
    ref_seq = []
    ref_state, _ = train_resumable(
        make_step(ref_seq), fresh(), _Loader(batches), 2, verbose=False)

    hc = HostCheckpoint(tmp_path)
    template = jax.tree.map(np.asarray, fresh())

    def save_fn(state, step, epoch, offset):
        hc.save(jax.tree.map(np.asarray, state), step,
                epoch=epoch, offset=offset)

    def restore_fn():
        res = hc.restore(template)
        if res is None:
            return None
        state, meta = res
        return jax.tree.map(jnp.asarray, state), meta

    class PreemptAt:
        def __init__(self, handler, step):
            self.handler, self.step = handler, step

        def maybe_fire(self, step):
            if step == self.step:
                self.handler.preempt_now()

    seq = []
    handler = PreemptionHandler()
    with pytest.raises(Preempted) as exc:
        train_resumable(
            make_step(seq), fresh(), _Loader(batches), 2,
            save_fn=save_fn, restore_fn=restore_fn, ckpt_every=2,
            preemption=handler, injector=PreemptAt(handler, preempt_step),
            prefetch=True, verbose=False)
    assert exc.value.step == preempt_step
    assert len(seq) == preempt_step  # nothing stepped past the boundary
    assert not [t for t in threading.enumerate()
                if t.name == "prefetch-loader" and t.is_alive()]

    state, report = train_resumable(
        make_step(seq), fresh(), _Loader(batches), 2,
        save_fn=save_fn, restore_fn=restore_fn, ckpt_every=2,
        preemption=PreemptionHandler(), prefetch=True, verbose=False)
    assert report.resumed_step == preempt_step
    np.testing.assert_array_equal(
        np.asarray(state["w"]), np.asarray(ref_state["w"]))
    assert seq == ref_seq  # no batch replayed, none skipped, same order


# -- schedule report fixture ------------------------------------------------

# Hand-written scheduled module covering both collective spellings: one
# async -start/-done pair bridging a backward dot, one sync all-reduce
# scheduled before the last backward dot (an interleaved issue point), one
# after it (exposed). Shapes sized to make the byte math obvious.
_CANNED_HLO = """\
HloModule canned, is_scheduled=true

%add (x: f32[], y: f32[]) -> f32[] {
  %x = f32[] parameter(0)
  %y = f32[] parameter(1)
  ROOT %a = f32[] add(f32[] %x, f32[] %y)
}

ENTRY %main (p0: f32[256]) -> f32[256] {
  %p0 = f32[256]{0} parameter(0)
  %dot.fwd = f32[256]{0} dot(f32[256]{0} %p0, f32[256]{0} %p0), metadata={op_name="jit(step)/fwd/dot_general"}
  %ar-start.1 = f32[256]{0} all-reduce-start(f32[256]{0} %dot.fwd), replica_groups={{0,1}}, to_apply=%add
  %dot.bwd1 = f32[256]{0} dot(f32[256]{0} %p0, f32[256]{0} %dot.fwd), metadata={op_name="jit(step)/transpose(jvp(fwd))/dot_general"}
  %ar-done.1 = f32[256]{0} all-reduce-done(f32[256]{0} %ar-start.1)
  %sync.early = f32[256]{0} all-reduce(f32[256]{0} %dot.bwd1), replica_groups={{0,1}}, to_apply=%add
  %dot.bwd2 = f32[128]{0} dot(f32[128]{0} %p0, f32[128]{0} %p0), metadata={op_name="jit(step)/transpose(fwd)/dot_general"}
  %sync.late = f32[128]{0} all-reduce(f32[128]{0} %dot.bwd2), replica_groups={{0,1}}, to_apply=%add
  ROOT %out = f32[256]{0} add(f32[256]{0} %ar-done.1, f32[256]{0} %sync.early)
}
"""


def test_schedule_report_on_canned_hlo():
    from hlo_schedule import schedule_report

    rep = schedule_report(_CANNED_HLO)
    assert rep["collective_count"] == 3
    assert rep["async_pairs"] == 1
    assert rep["sync_collectives"] == 2
    # async pair bridges dot.bwd1; sync.early precedes the last backward
    # dot; sync.late is scheduled after it -> exposed
    assert rep["overlapped_collectives"] == 2
    assert rep["last_bwd_compute_op"] == "dot.bwd2"
    assert rep["all_reduce_issues_before_last_bwd_compute"] == 2
    assert rep["comm_bytes_total"] == 1024 + 1024 + 512
    assert rep["comm_bytes_exposed"] == 512
    assert rep["exposed_comm_fraction"] == pytest.approx(512 / 2560)
    by_op = {c["op"]: c for c in rep["collectives"]}
    assert by_op["ar-start.1"]["form"] == "async"
    assert by_op["ar-start.1"]["compute_ops_between"] == 1
    assert by_op["sync.early"]["overlapped"] is True
    assert by_op["sync.late"]["overlapped"] is False


def test_schedule_report_monolithic_shape():
    """A single all-reduce after the last backward op — the monolithic
    baseline — must read as fully exposed with zero early issues."""
    from hlo_schedule import schedule_report

    text = _CANNED_HLO.splitlines()
    mono = "\n".join(
        l for l in text
        if "ar-start" not in l and "ar-done" not in l and "sync.early" not in l
    ).replace("f32[256]{0} %ar-done.1", "f32[256]{0} %dot.bwd1")
    rep = schedule_report(mono)
    assert rep["collective_count"] == 1
    assert rep["overlapped_collectives"] == 0
    assert rep["exposed_comm_fraction"] == 1.0
    assert rep["all_reduce_issues_before_last_bwd_compute"] == 0
