"""Mosaic (TPU) lowering checks for every Pallas kernel — WITHOUT a TPU.

VERDICT r01 weak #7: interpret-mode tests can't see Mosaic lowering
failures (r01's kernels indeed failed on the real chip with a block-shape
constraint: the last two block dims must be (8k, 128m)-aligned or equal
the array dims — caught only by the on-chip bench). Mosaic lowering runs
at MLIR-lowering time, not execution time, so ``lower(lowering_platforms=
("tpu",))`` on the CPU backend exercises the exact check that failed,
machine-independent. These tests pin it for the fwd kernel, both backward
kernels, the lse/partial variants the ring engines use, and the CE kernel,
across the shape classes the bench exercises (block-aligned, non-multiple
sequence lengths, bf16, head_dim below the lane width).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.ops.losses import cross_entropy_loss  # noqa: F401 (parity)
from tpu_sandbox.ops.pallas_attention import (
    flash_attention,
    flash_attention_lse,
    make_flash_bwd_lse,
)
from tpu_sandbox.ops.pallas_ce import pallas_cross_entropy


def _lower_tpu(fn, *args):
    jax.jit(fn).trace(*args).lower(lowering_platforms=("tpu",))


@pytest.mark.parametrize(
    "b,s,h,d,dt",
    [
        (2, 512, 4, 64, jnp.float32),
        (2, 384, 4, 64, jnp.bfloat16),   # non-multiple-of-block S
        (1, 1024, 8, 128, jnp.bfloat16),
    ],
)
def test_flash_attention_fwd_bwd_lowers_for_tpu(b, s, h, d, dt):
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(b, s, h, d)), dt)
               for _ in range(3))

    def loss(q, k, v):
        out = flash_attention(q, k, v, interpret=False)
        return jnp.sum(out.astype(jnp.float32))

    _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_flash_lse_and_partial_bwd_lower_for_tpu():
    """The ring engines' building blocks: forward-with-lse at unequal
    q/kv lengths + the per-hop partial backward factory."""
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 256, 2, 64)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(1, 384, 2, 64)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(1, 384, 2, 64)), jnp.bfloat16)

    def fwd(q, k, v):
        out, lse = flash_attention_lse(q, k, v, interpret=False,
                                       kv_offset=128)
        return out.astype(jnp.float32).sum() + lse.sum()

    _lower_tpu(fwd, q, k, v)

    def partial_bwd(q, k, v):
        out, lse = flash_attention_lse(q, k, v, interpret=False)
        g = jnp.ones_like(out)
        fn = make_flash_bwd_lse(q, out.astype(q.dtype), g.astype(q.dtype),
                                lse, interpret=False)
        dq, dk, dv = fn(k, v, 0)
        return dq.sum() + dk.sum() + dv.sum()

    _lower_tpu(partial_bwd, q, k, v)


def test_pallas_ce_lowers_for_tpu():
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(64, 32000)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 32000, size=(64,)), jnp.int32)
    _lower_tpu(
        lambda lg, lb: pallas_cross_entropy(lg, lb, interpret=False),
        logits, labels,
    )


@pytest.mark.parametrize("n,c", [(256, 32768), (64, 128 * 1024)])
def test_pallas_ce_reduced_blocks_lower_for_tpu(n, c):
    """The VMEM-budgeted row blocks (32 rows at 32k vocab, the 8-row floor
    at 128k) must still lower under Mosaic — the fixed 128-row block OOMed
    scoped VMEM at LM scale (found by a chipless v5e AOT compile)."""
    from tpu_sandbox.ops.pallas_ce import _block_rows
    from tpu_sandbox.ops.pallas_common import round_up

    assert _block_rows(round_up(c, 128)) is not None
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(size=(n, c)), jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, c, size=(n,)), jnp.int32)
    _lower_tpu(
        lambda lg, lb: pallas_cross_entropy(lg, lb, interpret=False),
        logits, labels,
    )


def test_pipeline_flash_stage_lowers_for_tpu():
    """The flash kernel reached through PipelineParallel's stage compute —
    jax.checkpoint(lax.scan over stacked per-layer params) around the
    Pallas call, fwd AND bwd (VERDICT r02 weak #4 done-criterion). Scoped
    to the stage computation: under shard_map JAX dispatches pallas_call
    lowering on the ACTUAL backend, so the full shard_map'd step cannot be
    cross-lowered for TPU from CPU ("Only interpret mode is supported on
    CPU backend"); the collectives around the stage are kernel-free and
    covered by the interpret-mode execution tests above this one."""
    import optax

    from tpu_sandbox.models.transformer import TransformerConfig
    from tpu_sandbox.ops.pallas_attention import flash_attention_fn
    from tpu_sandbox.parallel.pipeline import PipelineParallel
    from tpu_sandbox.runtime.mesh import make_mesh

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=4,
                            d_ff=64, max_len=256, dtype=jnp.bfloat16)
    mesh = make_mesh({"data": 2, "pipe": 4})
    pp = PipelineParallel(cfg, optax.sgd(0.1), mesh, microbatches=2,
                          donate=False,
                          attention_fn=flash_attention_fn(interpret=False))
    # init eagerly EXECUTES the model on CPU, where interpret=False would
    # fail — init through the dense twin instead (params are
    # attention_fn-independent, same tree either way)
    pp_dense = PipelineParallel(cfg, optax.sgd(0.1), mesh, microbatches=2,
                                donate=False)
    tokens = np.zeros((4, 256), np.int32)
    state = pp_dense.init_state(jax.random.key(0), jnp.asarray(tokens))
    # one stage's layer stack, as the tick loop slices it: [v, lps, ...] -> c=0
    stage = jax.tree.map(lambda x: x[0, 0], state.params["stages"])
    h = jnp.zeros((2, 256, cfg.d_model), cfg.dtype)

    def stage_loss(stage, h):
        out = jax.checkpoint(pp._stage_apply)(stage, h)
        return jnp.sum(out.astype(jnp.float32))

    _lower_tpu(jax.grad(stage_loss, argnums=(0, 1)), stage, h)


@pytest.mark.parametrize("c,co", [(16, 256), (64, 128)])
def test_pallas_conv_lowers_for_tpu(c, co):
    """The 3x3 s2d conv kernels (ops/pallas_conv.py) at the ConvNet's real
    per-layer widths (conv1: 16->256, conv2: 64->128, W=750), fwd + the
    full VJP (flipped-weight dgrad + fused wgrad/dbias) — manual-DMA halo
    strips and scratch accumulators must pass real Mosaic checks."""
    from tpu_sandbox.ops.pallas_conv import conv3x3

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((1, 20, 750, c)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((3, 3, c, co)), jnp.bfloat16)
    b = jnp.zeros((co,), jnp.bfloat16)

    def loss(x, k, b):
        return jnp.sum(conv3x3(x, k, b, False).astype(jnp.float32))

    _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), x, k, b)

    # the TPU-default train path runs the STATS variant (scratch
    # accumulators, pl.when init/emit, [1,co] stats outputs) — lower it too
    from tpu_sandbox.ops.pallas_conv import conv3x3_stats

    def loss_stats(x, k, b):
        y, s, ss = conv3x3_stats(x, k, b, False)
        return jnp.sum(y.astype(jnp.float32)) + jnp.sum(s) + jnp.sum(ss)

    _lower_tpu(jax.grad(loss_stats, argnums=(0, 1, 2)), x, k, b)


@pytest.mark.parametrize("blk,co,w", [(4, 16, 752), (2, 32, 752)])
def test_fused_bn_tail_lowers_for_tpu(blk, co, w):
    """The fused BN-apply+relu+pool kernels (ops/pallas_bn_tail.py) at the
    s2d ConvNet's real lane widths (C=256 and C=128) — forward and both
    backward kernels."""
    from tpu_sandbox.ops.pallas_bn_tail import fused_bn_relu_pool

    rng = np.random.default_rng(4)
    c = blk * blk * co
    y = jnp.asarray(rng.standard_normal((2, 10, w, c)), jnp.bfloat16)
    gamma = jnp.ones(co, jnp.float32)
    beta = jnp.zeros(co, jnp.float32)

    def loss(y, gamma, beta):
        out, _, _ = fused_bn_relu_pool(y, gamma, beta, co, blk, 1e-5, False)
        return jnp.sum(out.astype(jnp.float32))

    _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), y, gamma, beta)


@pytest.mark.parametrize("restage", ["gt", "auto"])
@pytest.mark.parametrize("c,co", [(16, 256), (64, 128)])
def test_pallas_conv_t_lowers_for_tpu(c, co, restage, monkeypatch):
    """VERDICT r03 next-6: the TRANSPOSED conv kernels
    (ops/pallas_conv_t.py) — the plan `auto` resolves to on TPU — at the
    production widths (conv1: 16->256, conv2: 64->128, W=750), fwd + the
    full VJP (flipped-weight dgrad + fused wgrad/dbias) and the stats
    variant, under real Mosaic lowering. Both wgrad restage variants
    (r05: explicit-gT native dot vs Mosaic's own lane-lane handling)."""
    from tpu_sandbox.ops.pallas_conv_t import conv3x3_t, conv3x3_t_stats

    monkeypatch.setenv("TPU_SANDBOX_WGRAD_RESTAGE", restage)
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.standard_normal((1, 20, c, 750)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((3, 3, c, co)), jnp.bfloat16)
    b = jnp.zeros((co,), jnp.bfloat16)

    def loss(x, k, b):
        return jnp.sum(conv3x3_t(x, k, b, False).astype(jnp.float32))

    _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), x, k, b)

    def loss_stats(x, k, b):
        y, s, ss = conv3x3_t_stats(x, k, b, False)
        return jnp.sum(y.astype(jnp.float32)) + jnp.sum(s) + jnp.sum(ss)

    _lower_tpu(jax.grad(loss_stats, argnums=(0, 1, 2)), x, k, b)


@pytest.mark.parametrize("blk,co", [(4, 16), (2, 32)])
def test_fused_bn_tail_t_lowers_for_tpu(blk, co):
    """The transposed fused BN/ReLU/pool pair (ops/pallas_bn_tail_t.py)
    at production channel heights (C=256, C=128) and W=750 — forward and
    both backward kernels."""
    from tpu_sandbox.ops.pallas_bn_tail_t import fused_bn_relu_pool_t

    rng = np.random.default_rng(10)
    c = blk * blk * co
    y = jnp.asarray(rng.standard_normal((2, 10, c, 750)), jnp.bfloat16)
    gamma = jnp.ones(co, jnp.float32)
    beta = jnp.zeros(co, jnp.float32)

    def loss(y, gamma, beta):
        out, _, _ = fused_bn_relu_pool_t(y, gamma, beta, co, blk, 1e-5,
                                         False)
        return jnp.sum(out.astype(jnp.float32))

    _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), y, gamma, beta)


def test_s2dt_train_step_lowers_for_tpu(monkeypatch):
    """The INTEGRATED default-TPU-plan train step — ConvNetS2DT with
    fused tails + conv-fused stats, the fused input stage, the in-layout
    fc, SGD — lowered for TPU at the real 3000x3000 geometry (bs=1).
    A lowering regression in the production plan fails HERE, not on the
    chip (VERDICT r03 next-6 done-criterion)."""
    import optax

    from tpu_sandbox.models.convnet_s2d_t import ConvNetS2DT
    from tpu_sandbox.train import TrainState, make_train_step

    monkeypatch.setenv("TPU_SANDBOX_FORCE_COMPILED_KERNELS", "1")
    model = ConvNetS2DT(dtype=jnp.bfloat16, fused_tail=True)
    tx = optax.sgd(1e-4)
    state = jax.eval_shape(
        lambda: TrainState.create(
            model, jax.random.key(0),
            jnp.zeros((1, 3000, 3000, 1), jnp.bfloat16), tx))
    step = make_train_step(model, tx, image_size=(3000, 3000),
                           donate=False)
    imgs = jax.ShapeDtypeStruct((1, 28, 28, 1), jnp.float32)
    labs = jax.ShapeDtypeStruct((1,), jnp.int32)
    jax.jit(step).trace(state, imgs, labs).lower(
        lowering_platforms=("tpu",))


@pytest.mark.parametrize("restage", ["gt", "auto"])
def test_sparse_tap_conv1_lowers_for_tpu(restage, monkeypatch):
    """The r04 sparse-tap conv1 (ops/pallas_conv5_t.py) at the
    production geometry (16 -> 256, W=750): fwd, stats, and the fused
    wgrad/dbias under real Mosaic — both wgrad restage variants."""
    from tpu_sandbox.ops.pallas_conv5_t import conv1_s2d_t, conv1_s2d_t_stats

    monkeypatch.setenv("TPU_SANDBOX_WGRAD_RESTAGE", restage)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((1, 20, 16, 750)), jnp.bfloat16)
    k5 = jnp.asarray(rng.standard_normal((5, 5, 1, 16)), jnp.bfloat16)
    b = jnp.zeros((16,), jnp.bfloat16)

    def loss(x, k, b):
        return jnp.sum(conv1_s2d_t(x, k, b, False).astype(jnp.float32))

    _lower_tpu(jax.grad(loss, argnums=(1, 2)), x, k5, b)

    def loss_stats(x, k, b):
        y, s, ss = conv1_s2d_t_stats(x, k, b, False)
        return jnp.sum(y.astype(jnp.float32)) + jnp.sum(s) + jnp.sum(ss)

    _lower_tpu(jax.grad(loss_stats, argnums=(1, 2)), x, k5, b)


def test_pallas_fc_dgrad_lowers_for_tpu():
    """The r05 fc input-grad kernel (ops/pallas_fc_t.py) at production
    geometry: K=10 classes, C=32, W=750, bs=16 — the scalar-FMA
    accumulation with g in SMEM, under real Mosaic."""
    from tpu_sandbox.ops.pallas_fc_t import fc_t

    rng = np.random.default_rng(12)
    y = jnp.asarray(rng.standard_normal((16, 30, 32, 750)), jnp.bfloat16)
    kernel = jnp.asarray(
        rng.standard_normal((30 * 32 * 750, 10)) * 1e-4, jnp.float32)
    bias = jnp.zeros((10,), jnp.float32)

    def loss(y, kernel, bias):
        return jnp.sum(fc_t(y, kernel, bias, jnp.bfloat16, False)
                       .astype(jnp.float32))

    _lower_tpu(jax.grad(loss, argnums=(0, 1, 2)), y, kernel, bias)


def test_conv1_tail_fused_bwd_lowers_for_tpu():
    """The r05 fused conv1+tail backward (ops/pallas_conv1_tail_t.py) at
    production geometry (16 -> 256, pool to 64, W=750): the combined
    tail-dy-recompute + sparse wgrad kernel, plus the unchanged reduce
    pass, under real Mosaic."""
    from tpu_sandbox.ops.pallas_conv1_tail_t import conv1_tail_t

    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.standard_normal((1, 20, 16, 750)), jnp.bfloat16)
    k5 = jnp.asarray(rng.standard_normal((5, 5, 1, 16)), jnp.bfloat16)
    cb = jnp.zeros((16,), jnp.bfloat16)
    gamma = jnp.ones((16,), jnp.float32)
    beta = jnp.zeros((16,), jnp.float32)

    def loss(k5, cb, gamma, beta):
        out, _, _ = conv1_tail_t(x, k5, cb, gamma, beta, 16, 4, 1e-5,
                                 False)
        return jnp.sum(out.astype(jnp.float32))

    _lower_tpu(jax.grad(loss, argnums=(0, 1, 2, 3)), k5, cb, gamma, beta)
