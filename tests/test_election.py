"""LeaseElection unit tests: acquire, renew, expire, steal, depose, resign,
and the orphaned-claim grace window — all in-process against one KVServer,
no subprocesses, short TTLs. The multi-process behavior (leader death
mid-generation, failover continuing the job) lives in the slow
test_multihost_elastic_integration module; this file pins the protocol."""

import time

import pytest

from tpu_sandbox.runtime.election import LeaderInfo, LeaseElection
from tpu_sandbox.runtime.kvstore import KVClient, KVServer

# KV round trips are sub-millisecond (TCP_NODELAY), but a TTL still has to
# dwarf a handful of them plus scheduler jitter under a loaded test box.
TTL = 0.5


@pytest.fixture()
def kv():
    with KVServer() as srv:
        clients = []

        def make():
            c = KVClient(port=srv.port)
            clients.append(c)
            return c

        yield make
        for c in clients:
            c.close()


def _member(kv, mid, **kw):
    kw.setdefault("ttl", TTL)
    return LeaseElection(kv(), mid, **kw)


def test_first_candidate_acquires_term_1(kv):
    a = _member(kv, "a")
    assert a.step() is True
    assert a.is_leader and a.term == 1
    assert a.observe() == LeaderInfo(1, "a")


def test_follower_observes_without_stealing(kv):
    a, b = _member(kv, "a"), _member(kv, "b")
    assert a.step() is True
    assert b.step() is False           # sees a's live lease, follows
    assert b.term == 1 and not b.is_leader
    assert b.observe() == LeaderInfo(1, "a")


def test_renewal_keeps_lease_past_ttl(kv):
    a, b = _member(kv, "a"), _member(kv, "b")
    assert a.step() is True
    deadline = time.monotonic() + 3 * TTL
    while time.monotonic() < deadline:
        assert a.step() is True        # renew well inside the TTL
        assert b.step() is False       # never a vacancy to elect into
        time.sleep(TTL / 3)
    assert a.term == 1                 # same term throughout: renewed, not re-won


def test_expired_lease_is_stolen_at_higher_term(kv):
    a, b = _member(kv, "a"), _member(kv, "b")
    assert a.step() is True
    time.sleep(TTL * 2)                # a stops renewing: lease evaporates
    assert b.step() is True
    assert b.term == 2                 # new term, not a resurrection of 1
    assert b.observe() == LeaderInfo(2, "b")


def test_stale_leader_abdicates_after_takeover(kv):
    a, b = _member(kv, "a"), _member(kv, "b")
    assert a.step() is True
    time.sleep(TTL * 2)
    assert b.step() is True            # term 2 established
    # a comes back (partition healed): sees the advanced term, steps down
    assert a.step() is False
    assert not a.is_leader and a.term == 2
    assert b.step() is True            # b unaffected


def test_non_candidate_never_elects_but_still_follows(kv):
    b = _member(kv, "b")
    assert b.step(candidate=False) is False
    assert b.observe() is None         # vacancy left untouched
    a = _member(kv, "a")
    assert a.step() is True
    assert b.step(candidate=False) is False
    assert b.term == 1                 # does follow the winner it observes


def test_resign_hands_off_without_waiting_out_ttl(kv):
    a, b = _member(kv, "a"), _member(kv, "b")
    assert a.step() is True
    a.resign()
    assert b.step() is True            # immediate: no TTL wait needed
    assert b.term == 2


def test_orphaned_claim_blocks_only_for_grace(kv):
    """A claimant that dies between claim and establish leaves a persistent
    claim key. Candidates wait out claim_grace on that term, then skip it —
    bounded stall, never a deadlock."""
    store = kv()
    store.add("leader/claim/1", 1)     # orphan: claimed, never established
    b = LeaseElection(kv(), "b", ttl=TTL, claim_grace=0.4)
    t0 = time.monotonic()
    assert b.step() is False           # inside the orphan's grace window
    while not b.step():
        assert time.monotonic() - t0 < 5.0, "grace window never expired"
        time.sleep(0.05)
    waited = time.monotonic() - t0
    assert waited >= 0.3               # did actually honor the grace
    assert b.term == 2                 # skipped the bricked term entirely


def test_claim_race_has_exactly_one_winner(kv):
    """All members run the same vacancy election; add() arbitration must
    produce exactly one leader no matter the interleaving."""
    # generous ttl: five sequential steps cost ~15 round-trips and the lease
    # must not lapse mid-pass, or a "second winner" is just a legal steal
    members = [_member(kv, str(i), ttl=5.0) for i in range(5)]
    results = [m.step() for m in members]
    assert sum(results) == 1
    leader = members[results.index(True)]
    assert all(m.term == leader.term for m in members)
    # and every later step agrees
    assert [m.step() for m in members] == results
