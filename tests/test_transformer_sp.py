"""Transformer + sequence-parallel engine tests.

Correctness bar: the SP train step (ring attention + pmean'd grads over
('data','sp')) must match single-device training of the identical model
with local attention on the same global batch."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
from tpu_sandbox.ops.losses import cross_entropy_loss
from tpu_sandbox.parallel.seq_parallel import SeqParallel
from tpu_sandbox.runtime.mesh import make_mesh
from tpu_sandbox.train import TrainState

CFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                        max_len=64)


def model_ctor(attention_fn):
    return TransformerLM(CFG, attention_fn)


def lm_data(b=4, s=32, seed=0):
    """Learnable task: next token = (token + 7) % vocab."""
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, CFG.vocab_size, size=(b, s)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)
    targets[:, -1] = (tokens[:, -1] + 7) % CFG.vocab_size
    targets = ((tokens + 7) % CFG.vocab_size).astype(np.int32)
    return tokens, targets


@pytest.fixture(scope="module")
def mesh_dp_sp():
    return make_mesh({"data": 2, "sp": 4})


def test_sp_step_matches_single_device(mesh_dp_sp):
    tx = optax.sgd(0.1)
    sp = SeqParallel(model_ctor, tx, mesh_dp_sp, donate=False)
    tokens, targets = lm_data()
    state = sp.init_state(jax.random.key(0), jnp.asarray(tokens))

    # single-device reference: same params, local attention, full batch
    local = sp.local_model

    def ref_loss(params):
        logits = local.apply({"params": params}, jnp.asarray(tokens))
        return cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]), jnp.asarray(targets).reshape(-1)
        )

    ref_loss_val, ref_grads = jax.value_and_grad(ref_loss)(state.params)
    ref_params = optax.apply_updates(
        state.params, tx.update(ref_grads, tx.init(state.params), state.params)[0]
    )

    sstate = sp.shard_state(state)
    new_state, loss = sp.train_step(sstate, *sp.shard_batch(tokens, targets))
    np.testing.assert_allclose(float(loss), float(ref_loss_val), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        new_state.params,
        ref_params,
    )


def test_sp_training_learns(mesh_dp_sp):
    tx = optax.adam(1e-2)
    sp = SeqParallel(model_ctor, tx, mesh_dp_sp, donate=False)
    tokens, targets = lm_data(b=8, s=32)
    state = sp.shard_state(sp.init_state(jax.random.key(1), jnp.asarray(tokens)))
    batch = sp.shard_batch(tokens, targets)
    losses = []
    for _ in range(30):
        state, loss = sp.train_step(state, *batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_sp_validates_axes():
    mesh = make_mesh({"data": 8})
    with pytest.raises(ValueError, match="not in mesh"):
        SeqParallel(model_ctor, optax.sgd(0.1), mesh)


def test_transformer_forward_shapes():
    model = TransformerLM(CFG)
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32


def test_transformer_is_causal():
    model = TransformerLM(CFG)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 64, (1, 16)), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    base = model.apply(variables, tokens)
    mutated = tokens.at[:, 10:].set(1)
    out = model.apply(variables, mutated)
    np.testing.assert_allclose(
        np.asarray(base)[:, :10], np.asarray(out)[:, :10], atol=1e-5
    )
