"""Full chaos fault matrix, slow tier (module auto-marked slow).

Three seeded campaigns drawn by :func:`build_schedule` over every fault
family the in-process harness can execute (gateway kill, replica shed
storm, replica stall), against a 3-gateway / 3-replica stub fleet. Each
must end with zero lost requests and a clean claim audit, and after the
wreckage a prefix probe checks failover didn't degrade the door to
blind load balancing. The real-process twin with TLS on the wire is
``bench.py --metric chaos``.
"""

import contextlib
import threading
import time

import numpy as np
import pytest

from tpu_sandbox.gateway.client import GatewayClient
from tpu_sandbox.gateway.fleet import FleetSpec
from tpu_sandbox.gateway.server import Gateway
from tpu_sandbox.models.transformer import TransformerConfig
from tpu_sandbox.obs import workload
from tpu_sandbox.runtime.chaos import (ChaosCampaign, build_schedule,
                                       check_alert_claims, prefix_probe)
from tpu_sandbox.serve.cache import CacheConfig, chain_digest
from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig

MCFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_len=128)
CCFG = CacheConfig(num_blocks=32, block_size=4, max_blocks_per_seq=8)
BLOCK = CCFG.block_size


class _StubStep:
    def __init__(self, buckets=(8, 16), vocab=64):
        self.buckets = tuple(buckets)
        self.vocab = vocab
        self.prefill = {b: self._prefill for b in self.buckets}

    def pick_bucket(self, plen):
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(f"prompt of {plen} exceeds buckets {self.buckets}")

    def _prefill(self, params, k, v, toks, dest, last):
        toks = np.asarray(toks)
        logits = np.zeros((self.vocab,), np.float32)
        logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
        return logits, k, v

    def decode(self, params, k, v, tokens, lengths, tables):
        tokens = np.asarray(tokens)
        logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
        for i in range(tokens.shape[0]):
            logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
        return logits, k, v


def _worker(kv, tag):
    from tpu_sandbox.serve.replica import ReplicaWorker

    cfg = ServeConfig(model=MCFG, cache=CCFG, max_batch=2, buckets=(8, 16))
    eng = ContinuousEngine(None, cfg, step=_StubStep(), clock=time.monotonic)
    return ReplicaWorker(kv, eng, tag=tag, lease_ttl=1.0, load_interval=0.02)


@contextlib.contextmanager
def _pumping(*workers):
    stop = threading.Event()

    def run():
        while not stop.is_set():
            for w in workers:
                w.tick()
            time.sleep(0.001)

    t = threading.Thread(target=run, name="chaos-pump", daemon=True)
    t.start()
    try:
        yield stop
    finally:
        stop.set()
        t.join(timeout=10.0)


def _run_matrix_campaign(seed):
    """One seeded campaign over the full in-process fault matrix."""
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    server = KVServer()
    kv = KVClient(port=server.port)
    clones = []

    def clone():
        c = kv.clone()
        clones.append(c)
        return c

    trace = workload.synthesize(seed, 16, duration_s=0.8,
                                prompt_tokens=(4, 10),
                                decode_tokens=(2, 4))
    # gw2 is never a kill candidate, so the client always has a door
    schedule = build_schedule(seed, duration_s=0.8, targets={
        "kill_gateway": ["gw0", "gw1"],
        "shed_storm": ["w0", "w1", "w2"],
        "stall_replica": ["w0:0.3", "w1:0.3", "w2:0.3"],
    }, n_faults=5)
    fleets = [FleetSpec(block_size=BLOCK)]
    gws = {
        gid: Gateway(kv, fleets, gateway_id=gid, hb_ttl=0.5,
                     refresh_min_s=0.005).start()
        for gid in ("gw0", "gw1", "gw2")
    }

    def kill_gateway(gid):
        if not gws[gid].killed:  # a seed may draw the same target twice
            gws[gid].kill()

    workers = [_worker(clone(), f"w{i}") for i in range(3)]
    client = None
    try:
        with _pumping(*workers):
            client = GatewayClient(
                endpoints=[("127.0.0.1", gws[g].port)
                           for g in ("gw0", "gw1", "gw2")],
                backoff_base=0.01)
            campaign = ChaosCampaign(
                clone(), trace, client.submit, seed=seed,
                schedule=schedule,
                hooks={"kill_gateway": kill_gateway},
                block_size=BLOCK, verdict_timeout=120.0)
            res = campaign.run()
            alert_failures = check_alert_claims(kv)
            routed = _probe_after(kv, client, campaign, trace, seed)
    finally:
        if client is not None:
            client.close()
        for g in gws.values():
            g.close()
        for c in clones:
            c.close()
        kv.close()
        server.stop()
    return res, alert_failures, routed


def _probe_after(kv, client, campaign, trace, seed, timeout=30.0):
    """Wait until some survivor advertises the chain's first block, then
    ask a surviving gateway to route one more request on that chain."""
    from tpu_sandbox.serve.replica import read_load_reports

    row = dict(workload.replay_order(trace)[0])
    row["prompt_tokens"] = max(int(row["prompt_tokens"]), BLOCK)
    prompt = campaign.prompt_for(row)
    head = chain_digest(prompt[:BLOCK], BLOCK)[0]
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        reports = read_load_reports(kv)
        if any(head in r.get("prefix_digest", ())
               for r in reports.values()):
            break
        time.sleep(0.02)
    else:
        raise AssertionError(f"no replica ever advertised block {head}")
    rid = f"probe-{seed}"
    routed = prefix_probe(client, prompt, rid)
    assert client.result(rid, timeout=60.0)["verdict"] == "ok"
    return routed


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_matrix_campaign_zero_loss(seed):
    res, alert_failures, routed = _run_matrix_campaign(seed)
    assert res.ok, res.failures
    assert res.lost == []
    assert res.submitted == 16 and len(res.verdicts) == 16
    assert all(v["verdict"] == "ok" and v["tokens"]
               for v in res.verdicts.values())
    assert len(res.fired) == 5
    assert alert_failures == []
    assert routed, "prefix routing never engaged after the campaign"


def test_distinct_seeds_draw_distinct_campaigns():
    targets = {"kill_gateway": ["gw0", "gw1"],
               "shed_storm": ["w0", "w1", "w2"],
               "stall_replica": ["w0:0.3", "w1:0.3", "w2:0.3"]}
    drawn = [tuple(build_schedule(s, duration_s=0.8, targets=targets,
                                  n_faults=5))
             for s in (101, 202, 303)]
    assert len(set(drawn)) == 3


def test_bench_chaos_cli_prints_one_json_line():
    """`bench.py --metric chaos --quick` end to end in a fresh
    interpreter: real gateway processes over TLS, a real SIGKILL, the
    claim audit and the tracediff gate. Quick mode is too small for the
    latency numbers to mean anything, so only the invariants are
    asserted; BENCH_r13.json holds a committed full run."""
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    proc = subprocess.run(
        [sys.executable, str(repo / "bench.py"),
         "--metric", "chaos", "--quick"],
        capture_output=True, text=True, timeout=540, cwd=str(repo),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "chaos"
    assert out["all_campaigns_green"] is True
    assert out["sigkill_zero_loss"] is True
    assert out["audit_replay_identical"] is True
    assert out["tls_plaintext_refused"] is True
    assert out["tracediff_gate_ok"] is True
    assert out["sigkill_campaign"]["failovers"] >= 1
