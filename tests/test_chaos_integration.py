"""Full chaos fault matrix, slow tier (module auto-marked slow).

Three seeded campaigns drawn by :func:`build_schedule` over every fault
family the in-process harness can execute (gateway kill, replica shed
storm, replica stall), against a 3-gateway / 3-replica stub fleet. Each
must end with zero lost requests and a clean claim audit, and after the
wreckage a prefix probe checks failover didn't degrade the door to
blind load balancing. The real-process twin with TLS on the wire is
``bench.py --metric chaos``.
"""

import contextlib
import threading
import time

import numpy as np
import pytest

from tpu_sandbox.gateway.client import GatewayClient
from tpu_sandbox.gateway.fleet import FleetSpec
from tpu_sandbox.gateway.server import Gateway
from tpu_sandbox.models.transformer import TransformerConfig
from tpu_sandbox.obs import workload
from tpu_sandbox.runtime.chaos import (ChaosCampaign, build_schedule,
                                       check_alert_claims, prefix_probe)
from tpu_sandbox.serve.cache import CacheConfig, chain_digest
from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig

MCFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_len=128)
CCFG = CacheConfig(num_blocks=32, block_size=4, max_blocks_per_seq=8)
BLOCK = CCFG.block_size


class _StubStep:
    def __init__(self, buckets=(8, 16), vocab=64):
        self.buckets = tuple(buckets)
        self.vocab = vocab
        self.prefill = {b: self._prefill for b in self.buckets}

    def pick_bucket(self, plen):
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(f"prompt of {plen} exceeds buckets {self.buckets}")

    def _prefill(self, params, k, v, toks, dest, last):
        toks = np.asarray(toks)
        logits = np.zeros((self.vocab,), np.float32)
        logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
        return logits, k, v

    def decode(self, params, k, v, tokens, lengths, tables):
        tokens = np.asarray(tokens)
        logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
        for i in range(tokens.shape[0]):
            logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
        return logits, k, v


def _worker(kv, tag):
    from tpu_sandbox.serve.replica import ReplicaWorker

    cfg = ServeConfig(model=MCFG, cache=CCFG, max_batch=2, buckets=(8, 16))
    eng = ContinuousEngine(None, cfg, step=_StubStep(), clock=time.monotonic)
    return ReplicaWorker(kv, eng, tag=tag, lease_ttl=1.0, load_interval=0.02)


@contextlib.contextmanager
def _pumping(*workers):
    stop = threading.Event()

    def run():
        while not stop.is_set():
            for w in workers:
                w.tick()
            time.sleep(0.001)

    t = threading.Thread(target=run, name="chaos-pump", daemon=True)
    t.start()
    try:
        yield stop
    finally:
        stop.set()
        t.join(timeout=10.0)


def _run_matrix_campaign(seed):
    """One seeded campaign over the full in-process fault matrix."""
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    server = KVServer()
    kv = KVClient(port=server.port)
    clones = []

    def clone():
        c = kv.clone()
        clones.append(c)
        return c

    trace = workload.synthesize(seed, 16, duration_s=0.8,
                                prompt_tokens=(4, 10),
                                decode_tokens=(2, 4))
    # gw2 is never a kill candidate, so the client always has a door
    schedule = build_schedule(seed, duration_s=0.8, targets={
        "kill_gateway": ["gw0", "gw1"],
        "shed_storm": ["w0", "w1", "w2"],
        "stall_replica": ["w0:0.3", "w1:0.3", "w2:0.3"],
    }, n_faults=5)
    fleets = [FleetSpec(block_size=BLOCK)]
    gws = {
        gid: Gateway(kv, fleets, gateway_id=gid, hb_ttl=0.5,
                     refresh_min_s=0.005).start()
        for gid in ("gw0", "gw1", "gw2")
    }

    def kill_gateway(gid):
        if not gws[gid].killed:  # a seed may draw the same target twice
            gws[gid].kill()

    workers = [_worker(clone(), f"w{i}") for i in range(3)]
    client = None
    try:
        with _pumping(*workers):
            client = GatewayClient(
                endpoints=[("127.0.0.1", gws[g].port)
                           for g in ("gw0", "gw1", "gw2")],
                backoff_base=0.01)
            campaign = ChaosCampaign(
                clone(), trace, client.submit, seed=seed,
                schedule=schedule,
                hooks={"kill_gateway": kill_gateway},
                block_size=BLOCK, verdict_timeout=120.0)
            res = campaign.run()
            alert_failures = check_alert_claims(kv)
            routed = _probe_after(kv, client, campaign, trace, seed)
    finally:
        if client is not None:
            client.close()
        for g in gws.values():
            g.close()
        for c in clones:
            c.close()
        kv.close()
        server.stop()
    return res, alert_failures, routed


def _probe_after(kv, client, campaign, trace, seed, timeout=30.0):
    """Wait until some survivor advertises the chain's first block, then
    ask a surviving gateway to route one more request on that chain."""
    from tpu_sandbox.serve.replica import read_load_reports

    row = dict(workload.replay_order(trace)[0])
    row["prompt_tokens"] = max(int(row["prompt_tokens"]), BLOCK)
    prompt = campaign.prompt_for(row)
    head = chain_digest(prompt[:BLOCK], BLOCK)[0]
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        reports = read_load_reports(kv)
        if any(head in r.get("prefix_digest", ())
               for r in reports.values()):
            break
        time.sleep(0.02)
    else:
        raise AssertionError(f"no replica ever advertised block {head}")
    rid = f"probe-{seed}"
    routed = prefix_probe(client, prompt, rid)
    assert client.result(rid, timeout=60.0)["verdict"] == "ok"
    return routed


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_matrix_campaign_zero_loss(seed):
    res, alert_failures, routed = _run_matrix_campaign(seed)
    assert res.ok, res.failures
    assert res.lost == []
    assert res.submitted == 16 and len(res.verdicts) == 16
    assert all(v["verdict"] == "ok" and v["tokens"]
               for v in res.verdicts.values())
    assert len(res.fired) == 5
    assert alert_failures == []
    assert routed, "prefix routing never engaged after the campaign"


def test_distinct_seeds_draw_distinct_campaigns():
    targets = {"kill_gateway": ["gw0", "gw1"],
               "shed_storm": ["w0", "w1", "w2"],
               "stall_replica": ["w0:0.3", "w1:0.3", "w2:0.3"]}
    drawn = [tuple(build_schedule(s, duration_s=0.8, targets=targets,
                                  n_faults=5))
             for s in (101, 202, 303)]
    assert len(set(drawn)) == 3


# -- agent-plane arm: kill_agent / partition_host against real HostAgents --
#
# The matrix campaigns above drive the serve fault mailbox and gateway
# kills; the agent actions (kill_agent, partition_host) were only ever
# exercised by the training-side fault matrix. This arm closes that gap:
# replicas run as rank SUBPROCESSES under real HostAgents (themselves
# subprocesses under AgentLauncher, so a kill_agent SIGKILL is a real
# process death and pdeathsig really takes the replica with it), and the
# campaign composes both agent actions mid-workload. A killed agent is
# respawned by the launcher, reports its lost ranks, and the leader
# bounces the whole gang to the next generation — the serve plane must
# ride through the bounce (leases lapse, peers scavenge, the queue
# drains) with zero lost requests. A partitioned agent goes silent on
# the control plane while its local replica keeps serving: the data
# plane must not notice.

_REPLICA_RANK = """
import os, sys, time
sys.path.insert(0, {root!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from tpu_sandbox.models.transformer import TransformerConfig
from tpu_sandbox.runtime.kvstore import KVClient
from tpu_sandbox.serve.cache import CacheConfig
from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig
from tpu_sandbox.serve.replica import ReplicaWorker


class Stub:
    def __init__(self, buckets=(8, 16), vocab=64):
        self.buckets = tuple(buckets)
        self.vocab = vocab
        self.prefill = dict.fromkeys(self.buckets, self._prefill)

    def pick_bucket(self, plen):
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError("prompt exceeds buckets")

    def _prefill(self, params, k, v, toks, dest, last):
        toks = np.asarray(toks)
        logits = np.zeros((self.vocab,), np.float32)
        logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
        return logits, k, v

    def decode(self, params, k, v, tokens, lengths, tables):
        tokens = np.asarray(tokens)
        logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
        for i in range(tokens.shape[0]):
            logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
        return logits, k, v


rank = int(sys.argv[1])
kv = KVClient(port=int(os.environ["TPU_SANDBOX_KV_PORT"]))
mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_len=128)
ccfg = CacheConfig(num_blocks=32, block_size=4, max_blocks_per_seq=8)
cfg = ServeConfig(model=mcfg, cache=ccfg, max_batch=2, buckets=(8, 16))
eng = ContinuousEngine(None, cfg, step=Stub(), clock=time.monotonic)
w = ReplicaWorker(kv, eng, tag="h%d" % rank, lease_ttl=1.0,
                  load_interval=0.02)
while kv.try_get("chaos/fleet_stop") is None:
    w.tick()
    time.sleep(0.001)
kv.close()
sys.exit(0)
"""

_AGENT_MAIN = """
import sys
sys.path.insert(0, {root!r})
from tpu_sandbox.runtime.host_agent import AgentConfig, HostAgent

aid, port, replica = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
cfg = AgentConfig(
    agent_id=aid, num_agents={n}, world_size={n}, kv_port=port,
    heartbeat_interval=0.1, agent_timeout=3.0, grace=30.0, lease_ttl=0.8,
    poll=0.02, term_timeout=5.0, ack_timeout=10.0, agent_wait=60.0,
    max_restarts=8, backoff=0.1, backoff_max=0.5)


def rank_cmd(gen, rank, coord_port):
    return [sys.executable, replica, str(rank)]


sys.exit(HostAgent(cfg, rank_cmd).run())
"""

N_AGENTS = 3


@pytest.mark.parametrize("seed", [404])
def test_agent_campaign_kill_and_partition_zero_loss(tmp_path, seed):
    import json
    import os
    import sys

    from tpu_sandbox.runtime.faults import agent_cmd_key
    from tpu_sandbox.runtime.host_agent import (AgentLauncher, K_JOB_DONE,
                                                K_RESTARTS)
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve.replica import read_load_reports

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    replica = tmp_path / "replica_rank.py"
    replica.write_text(_REPLICA_RANK.format(root=root))
    agent = tmp_path / "host_agent_main.py"
    agent.write_text(_AGENT_MAIN.format(root=root, n=N_AGENTS))

    server = KVServer()
    kv = KVClient(port=server.port)
    clones = []

    def clone():
        c = kv.clone()
        clones.append(c)
        return c

    launcher = AgentLauncher(
        N_AGENTS,
        lambda aid, port: [sys.executable, str(agent), str(aid), str(port),
                           str(replica)],
        kv_server=server, poll=0.05, drain_timeout=30.0,
        extra_env={"JAX_PLATFORMS": "cpu"}, verbose=True,
    )
    outcome = {}
    lt = threading.Thread(
        target=lambda: outcome.setdefault("code", launcher.run()),
        name="agent-launcher", daemon=True)
    lt.start()

    trace = workload.synthesize(seed, 12, duration_s=1.0,
                                prompt_tokens=(4, 10), decode_tokens=(2, 4))
    # agent 0 carries the election bias and rank 0's coordinator duty;
    # keeping it out of the pools keeps the control plane warm (same
    # shape as gw2 never being a kill candidate above). Both remaining
    # agents are fair game for both actions.
    schedule = build_schedule(seed, duration_s=1.0, targets={
        "kill_agent": ["1", "2"],
        "partition_host": ["1:1.2", "2:1.2"],
    }, n_faults=3)

    def kill_agent(target):
        kv.set(agent_cmd_key(int(target)),
               json.dumps({"action": "kill_agent", "arg": None}))

    def partition_host(target):
        aid, _, dur = target.partition(":")
        kv.set(agent_cmd_key(int(aid)),
               json.dumps({"action": "partition_host", "arg": float(dur)}))

    gws = {}
    client = None
    try:
        # wait for generation 1's replicas to report for duty before
        # opening the door (fresh interpreters pay the jax import)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if len(read_load_reports(kv)) >= N_AGENTS:
                break
            assert lt.is_alive(), "launcher died before the fleet was up"
            time.sleep(0.05)
        else:
            raise AssertionError("replicas never reported for duty")

        gws = {
            gid: Gateway(kv, [FleetSpec(block_size=BLOCK)], gateway_id=gid,
                         hb_ttl=0.5, refresh_min_s=0.005).start()
            for gid in ("gw0", "gw1")
        }
        client = GatewayClient(
            endpoints=[("127.0.0.1", g.port) for g in gws.values()],
            backoff_base=0.01)
        campaign = ChaosCampaign(
            clone(), trace, client.submit, seed=seed, schedule=schedule,
            hooks={"kill_agent": kill_agent,
                   "partition_host": partition_host},
            block_size=BLOCK, verdict_timeout=240.0)
        res = campaign.run()
        alert_failures = check_alert_claims(kv)

        # retire the fleet: ranks exit 0, agents converge on an ok verdict
        kv.set("chaos/fleet_stop", b"1")
        lt.join(timeout=120.0)
        assert not lt.is_alive(), "launcher never reached a verdict"
    finally:
        if client is not None:
            client.close()
        for g in gws.values():
            g.close()
        if lt.is_alive():  # belt and braces: unblock the join on failure
            kv.set("chaos/fleet_stop", b"1")
        verdict_raw = kv.try_get(K_JOB_DONE)
        restarts = int(kv.try_get(K_RESTARTS) or 0)
        for c in clones:
            c.close()
        kv.close()
        server.stop()

    assert res.ok, res.failures
    assert res.lost == []
    assert res.submitted == 12 and len(res.verdicts) == 12
    assert all(v["verdict"] == "ok" and v["tokens"]
               for v in res.verdicts.values())
    assert len(res.fired) == 3
    assert alert_failures == []
    assert outcome.get("code") == 0
    verdict = json.loads(verdict_raw)
    assert verdict["ok"], verdict
    fired = {f["action"] for f in res.fired}
    assert fired <= {"kill_agent", "partition_host"}
    if "kill_agent" in fired:
        # every SIGKILLed agent came back through the launcher, and the
        # leader charged the gang bounce to the restart budget
        assert launcher.respawns >= 1
        assert restarts >= 1


def test_bench_chaos_cli_prints_one_json_line():
    """`bench.py --metric chaos --quick` end to end in a fresh
    interpreter: real gateway processes over TLS, a real SIGKILL, the
    claim audit and the tracediff gate. Quick mode is too small for the
    latency numbers to mean anything, so only the invariants are
    asserted; BENCH_r13.json holds a committed full run."""
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    proc = subprocess.run(
        [sys.executable, str(repo / "bench.py"),
         "--metric", "chaos", "--quick"],
        capture_output=True, text=True, timeout=540, cwd=str(repo),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "chaos"
    assert out["all_campaigns_green"] is True
    assert out["sigkill_zero_loss"] is True
    assert out["audit_replay_identical"] is True
    assert out["tls_plaintext_refused"] is True
    assert out["tracediff_gate_ok"] is True
    assert out["sigkill_campaign"]["failovers"] >= 1
