"""Elastic supervisor: detect → teardown → relaunch, with the budget and
classification rules. Workers here are tiny ``python -c`` scripts (no jax)
so every case runs in seconds inside tier-1.

The real-training variants (kill a rank mid-epoch, resume, loss parity)
live in test_elastic_integration.py, marked slow.
"""

import os
import sys
import textwrap
from pathlib import Path

import pytest

from tpu_sandbox.runtime.kvstore import KVClient, KVServer
from tpu_sandbox.runtime.supervisor import (
    PREEMPTED_EXIT_CODE,
    PREEMPT_KEY,
    RestartBudgetExceeded,
    Supervisor,
)


def test_constants_mirror_trainer():
    """supervisor.py and trainer.py deliberately do not import each other;
    this pin is what keeps their shared constants from drifting."""
    from tpu_sandbox.train import trainer

    assert trainer.PREEMPTED_EXIT_CODE == PREEMPTED_EXIT_CODE
    assert trainer.PREEMPT_KEY == PREEMPT_KEY


# workers must import tpu_sandbox no matter where pytest was launched from
_REPO_ROOT = str(Path(__file__).resolve().parent.parent)
_EXTRA_ENV = {
    "PYTHONPATH": _REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
}


def _worker(body: str) -> list[str]:
    """A rank as a self-contained python -c script."""
    return [sys.executable, "-c", textwrap.dedent(body)]


def _exit_with(code: int) -> list[str]:
    return _worker(f"import sys; sys.exit({code})")


def _beating_worker(rank: int, body: str) -> list[str]:
    """A rank that heartbeats into the supervisor's store, then runs body."""
    return _worker(f"""
        import os, sys, time
        from tpu_sandbox.runtime.kvstore import KVClient
        from tpu_sandbox.runtime.watchdog import Heartbeat
        kv = KVClient(port=int(os.environ["TPU_SANDBOX_KV_PORT"]))
        hb = Heartbeat(kv, {rank}, interval=0.05).start()
        {body}
    """)


def test_clean_generation_is_ok():
    sup = Supervisor(
        2, lambda gen, port: [_exit_with(0), _exit_with(0)],
        backoff=0.05, poll=0.02, verbose=False, extra_env=_EXTRA_ENV,
    )
    result = sup.run()
    assert result.ok
    assert result.restarts_charged == 0 and result.preemptions == 0
    assert [g.outcome for g in result.generations] == ["ok"]


def test_crash_restarts_and_recovers():
    """Generation 1: rank 1 dies. Generation 2: everyone behaves. The
    supervisor must tear down the survivor, charge one restart, relaunch."""
    def build(gen, port):
        if gen == 1:
            return [_worker("import time; time.sleep(30)"), _exit_with(1)]
        return [_exit_with(0), _exit_with(0)]

    sup = Supervisor(2, build, backoff=0.05, poll=0.02,
                     term_timeout=5.0, verbose=False, extra_env=_EXTRA_ENV)
    result = sup.run()
    assert result.ok
    assert result.restarts_charged == 1
    gens = result.generations
    assert [g.outcome for g in gens] == ["failure", "ok"]
    assert gens[0].culprits == [1]  # the initiator, not the torn-down peer
    assert gens[0].exit_codes[1] == 1


def test_restart_budget_exceeded():
    sup = Supervisor(
        1, lambda gen, port: [_exit_with(3)],
        max_restarts=2, backoff=0.02, poll=0.02, verbose=False, extra_env=_EXTRA_ENV,
    )
    with pytest.raises(RestartBudgetExceeded, match="restart budget"):
        sup.run()
    # the exception carries the history: 3 failed generations, budget spent
    try:
        sup = Supervisor(1, lambda gen, port: [_exit_with(3)],
                         max_restarts=1, backoff=0.02, poll=0.02,
                         verbose=False, extra_env=_EXTRA_ENV)
        sup.run()
    except RestartBudgetExceeded as e:
        assert len(e.result.generations) == 2
        assert all(g.outcome == "failure" for g in e.result.generations)
        assert e.result.restarts_charged == 2


def test_preemption_not_charged():
    """Exit 75 = "saved, restart me for free": no restart charged, prompt
    relaunch, and the run still ends ok."""
    def build(gen, port):
        if gen == 1:
            return [_exit_with(PREEMPTED_EXIT_CODE),
                    _exit_with(PREEMPTED_EXIT_CODE)]
        return [_exit_with(0), _exit_with(0)]

    sup = Supervisor(2, build, max_restarts=0, backoff=0.05, poll=0.02,
                     verbose=False, extra_env=_EXTRA_ENV)
    result = sup.run()  # max_restarts=0: any charged restart would raise
    assert result.ok
    assert result.preemptions == 1 and result.restarts_charged == 0
    assert [g.outcome for g in result.generations] == ["preemption", "ok"]


def test_preemption_initiator_only_classification():
    """Rank 0 exits preempted; rank 1 is blocked (a peer in a dead
    collective) and only dies to the supervisor's own SIGTERM. The
    teardown-produced code must not turn the preemption into a failure."""
    def build(gen, port):
        if gen == 1:
            return [
                _exit_with(PREEMPTED_EXIT_CODE),
                _worker("import time\ntime.sleep(60)"),  # ignores nothing, but dies to SIGTERM
            ]
        return [_exit_with(0), _exit_with(0)]

    sup = Supervisor(2, build, max_restarts=0, backoff=0.05, poll=0.02,
                     term_timeout=5.0, verbose=False, extra_env=_EXTRA_ENV)
    result = sup.run()
    assert result.ok
    assert result.preemptions == 1 and result.restarts_charged == 0
    assert result.generations[0].culprits == [0]


def test_wedged_rank_detected_by_watchdog():
    """A rank that stops heartbeating but never exits can only be caught by
    the heartbeat plane; exit-code polling would wait forever."""
    def build(gen, port):
        if gen == 1:
            return [
                # beats once (synchronously, via start()), then goes silent
                # while staying alive
                _beating_worker(0, "hb.stop(); time.sleep(60)"),
            ]
        return [_exit_with(0)]

    sup = Supervisor(1, build, heartbeat_timeout=0.6, grace=2.0,
                     backoff=0.05, poll=0.05, term_timeout=5.0,
                     verbose=False, extra_env=_EXTRA_ENV)
    result = sup.run()
    assert result.ok
    assert [g.outcome for g in result.generations] == ["wedged", "ok"]
    assert result.restarts_charged == 1


def test_health_plane_reset_between_generations():
    """Generation 2 must not inherit generation 1's frozen heartbeat or
    rendezvous keys — stale state would read as instant death / satisfied
    rendezvous. Also: the preempt flag must be cleared."""
    with KVServer() as srv:
        kv = KVClient(port=srv.port)
        # poison the plane the way a dead generation would
        kv.set("hb/0", b"123.0")
        kv.set("rendezvous/gen/0", b"1")
        kv.set(PREEMPT_KEY, b"1")

        sup = Supervisor(
            1, lambda gen, port: [_exit_with(0)],
            backoff=0.05, poll=0.02, heartbeat_timeout=0.5, grace=5.0,
            kv_server=srv, verbose=False, extra_env=_EXTRA_ENV,
        )
        result = sup.run()
        assert result.ok  # frozen hb/0 stamp did not read as a dead rank
        assert kv.try_get(PREEMPT_KEY) is None
        assert kv.try_get("rendezvous/gen/0") is None
        kv.close()


def test_worker_env_carries_kv_port_and_generation():
    """Workers learn the store and their generation from the env."""
    probe = _worker("""
        import os, sys
        from tpu_sandbox.runtime.kvstore import KVClient
        kv = KVClient(port=int(os.environ["TPU_SANDBOX_KV_PORT"]))
        kv.set("probe/gen", os.environ["TPU_SANDBOX_GENERATION"].encode())
        sys.exit(0)
    """)
    with KVServer() as srv:
        sup = Supervisor(1, lambda gen, port: [probe],
                         kv_server=srv, backoff=0.05, poll=0.02,
                         verbose=False, extra_env=_EXTRA_ENV)
        assert sup.run().ok
        kv = KVClient(port=srv.port)
        assert kv.try_get("probe/gen") == b"1"
        kv.close()
