"""SLO guardrail layer, fast and in-process (tier-1).

Everything here runs the real engine/replica/client/autoscaler code paths
with a *stub* decode step (next token = last token + 1 mod vocab) — no jax
compiles, so the whole file stays inside the tier-1 budget, the pattern
test_scheduler.py uses for the cluster layer. The real-model SLO paths are
covered by the slow-marked overload bench (bench.py --metric serve_slo)
and the chaos matrix in test_serve_slo_integration.py.
"""

import json
import time

import numpy as np
import pytest

from tpu_sandbox.models.transformer import TransformerConfig
from tpu_sandbox.serve.cache import CacheConfig
from tpu_sandbox.serve.engine import ContinuousEngine, Request, ServeConfig

MCFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_len=128)
CCFG = CacheConfig(num_blocks=24, block_size=4, max_blocks_per_seq=8)


class _StubStep:
    """DecodeStep stand-in: next token = (last token + 1) % vocab, no jax.
    Deterministic like the real step, so requeue-replay still reproduces."""

    def __init__(self, buckets=(8, 16), vocab=64):
        self.buckets = tuple(buckets)
        self.vocab = vocab
        self.prefill = {b: self._prefill for b in self.buckets}

    def pick_bucket(self, plen):
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(f"prompt of {plen} exceeds buckets {self.buckets}")

    def _prefill(self, params, k, v, toks, dest, last):
        toks = np.asarray(toks)
        logits = np.zeros((self.vocab,), np.float32)
        logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
        return logits, k, v

    def decode(self, params, k, v, tokens, lengths, tables):
        tokens = np.asarray(tokens)
        logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
        for i in range(tokens.shape[0]):
            logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
        return logits, k, v


class _Clock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


def _engine(clock=None, **over):
    cfg = ServeConfig(model=MCFG, cache=CCFG, max_batch=2, buckets=(8, 16),
                      **over)
    return ContinuousEngine(None, cfg, step=_StubStep(),
                            clock=clock or _Clock())


def _req(rid, n=3, **kw):
    return Request(rid=rid, prompt=[1, 2, 3], max_new_tokens=n, **kw)


# -- engine guardrails --------------------------------------------------------


def test_stub_engine_serves_end_to_end():
    eng = _engine()
    eng.submit(_req("r0", n=4))
    eng.run_until_idle()
    # next-token stub: 3 -> 4 -> 5 -> 6 -> 7
    assert eng.results["r0"].tokens == [4, 5, 6, 7]
    assert not eng.shed


def test_bounded_queue_sheds_incoming_with_verdict():
    eng = _engine(max_waiting=2)
    assert eng.submit(_req("r0"))
    assert eng.submit(_req("r1"))
    assert not eng.submit(_req("r2"))
    assert eng.shed["r2"].reason == "queue_full"
    # shed is terminal and exclusive: never also queued
    assert [r.rid for r in eng.waiting] == ["r0", "r1"]
    eng.drain_to_requests()


def test_overload_sheds_oldest_past_deadline_first():
    clock = _Clock()
    eng = _engine(clock, max_waiting=2)
    eng.submit(_req("r0", deadline=1.0))
    eng.submit(_req("r1"))
    clock.advance(2.0)  # r0 is now past its deadline
    assert eng.submit(_req("r2"))  # takes the slot r0's shed frees
    assert eng.shed["r0"].reason == "deadline"
    assert [r.rid for r in eng.waiting] == ["r1", "r2"]
    eng.drain_to_requests()


def test_no_result_ever_lands_past_deadline():
    clock = _Clock()
    eng = _engine(clock)
    # expires while waiting: shed before admission
    eng.submit(_req("rw", deadline=1.0))
    clock.advance(2.0)
    eng.step()
    assert eng.shed["rw"].reason == "deadline" and "rw" not in eng.results
    # expires while active: shed mid-flight, blocks returned
    free0 = eng.cache.free_blocks
    eng.submit(_req("ra", n=20, deadline=5.0))
    eng.step()  # admit + prefill
    assert eng.active_requests == 1
    clock.advance(10.0)
    eng.step()
    assert eng.shed["ra"].reason == "deadline" and "ra" not in eng.results
    assert eng.active_requests == 0 and eng.cache.free_blocks == free0
    # finishes past deadline (deadline passes inside the final step):
    # verdict is SHED, not a late result
    eng.submit(_req("rf", n=1, deadline=clock.t + 0.5))
    clock.advance(0.4)

    real_pick = eng._pick_token

    def slow_pick(slot, row):
        clock.advance(1.0)  # the step outlives the deadline
        return real_pick(slot, row)

    eng._pick_token = slow_pick
    eng.step()
    assert eng.shed["rf"].reason == "deadline" and "rf" not in eng.results


def test_load_report_signals():
    clock = _Clock()
    eng = _engine(clock, max_waiting=8)
    for i in range(4):
        eng.submit(_req(f"r{i}", n=6))
    eng.step()
    clock.advance(3.0)
    rep = eng.load_report()
    assert rep["active"] == 2 and rep["queue_depth"] == 2
    assert 0.0 < rep["free_block_frac"] < 1.0
    assert rep["step_age"] == pytest.approx(3.0)
    eng.run_until_idle()


# -- replica verdicts, load reports, fault mailbox ---------------------------


@pytest.fixture
def kv_pair():
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    server = KVServer()
    kv = KVClient(port=server.port)
    yield server, kv
    kv.close()
    server.stop()


def _worker(kv, **over):
    from tpu_sandbox.serve.replica import ReplicaWorker

    eng_over = {k: over.pop(k) for k in ("max_waiting",) if k in over}
    over.setdefault("lease_ttl", 1.0)
    return ReplicaWorker(kv, _engine(**eng_over), **over)


def test_replica_publishes_shed_verdicts_and_results(kv_pair):
    from tpu_sandbox.serve import replica as R

    _, kv = kv_pair
    w = _worker(kv, tag="w0")
    R.submit_request(kv, "ok0", [1, 2, 3], 3)
    # already expired at claim time: must still terminate with a verdict
    R.submit_request(kv, "late0", [1, 2, 3], 3,
                     deadline_unix=time.time() - 5.0)
    R.announce_total(kv, 2)
    w.run(timeout=30.0)
    ok = json.loads(kv.get(R.k_result("ok0")))
    late = json.loads(kv.get(R.k_result("late0")))
    assert ok["verdict"] == "ok" and ok["tokens"] == [4, 5, 6]
    assert late["verdict"] == "SHED" and late["reason"] == "deadline"
    assert R.results_done(kv)
    assert w.stats.completed == 1 and w.stats.shed == 1


def test_verdict_is_claim_once(kv_pair):
    from tpu_sandbox.serve import replica as R

    _, kv = kv_pair
    a, b = _worker(kv, tag="wa"), _worker(kv, tag="wb")
    # same rid executed by both (scavenged-duplicate shape): one verdict
    R.submit_request(kv, "dup", [1, 2, 3], 2)
    R.enqueue(kv, "dup")  # duplicate queue entry
    a._publish_verdict("dup", {"rid": "dup", "verdict": "SHED",
                               "reason": "test", "replica": "wa"})
    b._publish_verdict("dup", {"rid": "dup", "verdict": "ok",
                               "tokens": [4, 5], "replica": "wb"})
    got = json.loads(kv.get(R.k_result("dup")))
    assert got["verdict"] == "SHED" and got["replica"] == "wa"
    a.engine.drain_to_requests()
    b.engine.drain_to_requests()


def test_replica_load_report_published(kv_pair):
    from tpu_sandbox.serve import replica as R

    _, kv = kv_pair
    w = _worker(kv, tag="w0", load_interval=0.01)
    R.submit_request(kv, "r0", [1, 2, 3], 2)
    R.announce_total(kv, 1)
    w.run(timeout=30.0)
    reports = R.read_load_reports(kv)
    assert "w0" in reports
    assert {"queue_depth", "active", "free_block_frac",
            "step_age"} <= set(reports["w0"])


def test_shed_storm_fault_sheds_local_queue(kv_pair):
    from tpu_sandbox.runtime.faults import serve_cmd_key
    from tpu_sandbox.serve import replica as R

    _, kv = kv_pair
    w = _worker(kv, tag="w0")
    for i in range(4):
        R.submit_request(kv, f"r{i}", [1, 2, 3], 2)
    R.announce_total(kv, 4)
    w.tick()  # claims land: max_batch in slots, the rest waiting locally
    assert len(w.engine.waiting) >= 1
    kv.set(serve_cmd_key("w0"), json.dumps({"action": "shed_storm"}))
    w.run(timeout=30.0)
    verdicts = [json.loads(kv.get(R.k_result(f"r{i}")))["verdict"]
                for i in range(4)]
    # every request terminated; the storm shed whatever was queued locally
    # at fire time (claim_depth 4 > max_batch 2, so some were waiting)
    assert verdicts.count("SHED") >= 1
    assert set(verdicts) <= {"ok", "SHED"}
    assert R.results_done(kv)


# -- client: retry on shed, hedging ------------------------------------------


def test_client_retries_shed_then_succeeds(kv_pair):
    from tpu_sandbox.serve import replica as R
    from tpu_sandbox.serve.client import ServeClient

    _, kv = kv_pair
    client = ServeClient(kv, deadline_s=30.0, max_retries=2)
    client.submit("r0", [1, 2, 3], 3)
    # one replica sheds it (storm verdict), a second serves the retry
    storm = _worker(kv, tag="storm")
    storm._publish_verdict("r0", {"rid": "r0", "verdict": "SHED",
                                  "reason": "fault:shed_storm",
                                  "replica": "storm"})
    w = _worker(kv, tag="w0")
    # serve the retried entry in the background of the client poll: run a
    # few worker ticks interleaved by polling with a short timeout first
    got = None
    for _ in range(200):
        try:
            got = client.result("r0", timeout=0.05)
            break
        except TimeoutError:
            w.tick()
    assert got is not None and got["verdict"] == "ok"
    assert got["tokens"] == [4, 5, 6]
    assert client.stats.retries == 1


def test_client_raises_retries_exhausted_after_budget(kv_pair):
    from tpu_sandbox.serve.client import RetriesExhausted, ServeClient

    _, kv = kv_pair
    client = ServeClient(kv, max_retries=1)
    # deadline already burnt: every execution sheds
    client.submit("r0", [1, 2, 3], 3, deadline_s=-1.0)
    w = _worker(kv, tag="w0")
    err = None
    for _ in range(200):
        try:
            client.result("r0", timeout=0.05)
            raise AssertionError("terminal shed must raise, not return")
        except TimeoutError:
            w.tick()
        except RetriesExhausted as e:
            err = e
            break
    assert err is not None
    assert err.rid == "r0" and err.last_reason == "deadline"
    assert err.verdict["verdict"] == "SHED"
    # the per-attempt timeline: the original submit plus one retry, each
    # stamped with its shed reason once resolved
    assert len(err.attempts) == 2
    assert all("submitted_at" in a for a in err.attempts)
    assert [a["shed_reason"] for a in err.attempts] == ["deadline"] * 2
    assert client.stats.retries == 1 and client.stats.shed == 1


def test_client_hedges_lost_claim(kv_pair):
    from tpu_sandbox.serve import replica as R
    from tpu_sandbox.serve.client import ServeClient

    _, kv = kv_pair
    client = ServeClient(kv, deadline_s=30.0, hedge_after=0.01)
    client.submit("r0", [1, 2, 3], 3)
    # entry 0 claimed by a replica that died before leasing: no lease, no
    # result, nobody will ever finish it. Scavenge is parked (interval far
    # out) so the hedge path, not the scavenger, must do the rescue.
    assert kv.add(R.k_claim(0)) == 1
    time.sleep(0.02)
    w = _worker(kv, tag="w1", lease_ttl=0.2, scavenge_interval=60.0)
    got = None
    for _ in range(200):
        try:
            got = client.result("r0", timeout=0.05)
            break
        except TimeoutError:
            w.tick()
    assert got is not None and got["verdict"] == "ok"
    assert got["tokens"] == [4, 5, 6]
    assert client.stats.hedges == 1


# -- autoscaler ---------------------------------------------------------------


ARGV = ["python", "-m", "tpu_sandbox.serve.replica", "--config", "{job_id}"]


def _reports(kv, depths, ttl=10.0):
    from tpu_sandbox.serve.replica import k_load

    for tag, depth in depths.items():
        kv.set_ttl(k_load(tag), json.dumps({"queue_depth": depth}), ttl)


def test_autoscaler_bootstrap_grow_shrink(kv_pair):
    from tpu_sandbox.runtime.scheduler import k_cancel, list_jobs
    from tpu_sandbox.serve.autoscale import (AutoscaleConfig,
                                             ReplicaAutoscaler,
                                             autoscale_events)

    _, kv = kv_pair
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=2, hysteresis_ticks=2,
                          cooldown_s=0.0)
    a = ReplicaAutoscaler(kv, ARGV, cfg=cfg)
    # bootstrap to the floor, no hysteresis needed
    ev = a.tick()
    assert ev and ev["action"] == "scale_up" and ev["reason"] == "min_replicas"
    assert len(a.replica_jobs()) == 1
    # sustained overload: needs hysteresis_ticks consecutive signals
    _reports(kv, {"w0": 10.0})
    assert a.tick() is None
    ev = a.tick()
    assert ev and ev["action"] == "scale_up" and ev["reason"] == "queue_depth"
    assert len(a.replica_jobs()) == 2
    # capped at max_replicas even under continued overload
    assert a.tick() is None and a.tick() is None
    assert len(a.replica_jobs()) == 2
    # drained queues: scale back down to the floor, never below
    _reports(kv, {"w0": 0.0})
    assert a.tick() is None
    ev = a.tick()
    assert ev and ev["action"] == "scale_down"
    cancelled = ev["job_id"]
    assert kv.try_get(k_cancel(cancelled)) is not None
    # timeline reconstructable from the store
    actions = [e["action"] for e in autoscale_events(kv)]
    assert actions == ["scale_up", "scale_up", "scale_down"]
    # the gang jobs carry the serve tenancy for colocation
    for j in list_jobs(kv):
        if j["state"] == "queued":
            assert j["tenant"] == "serve" and j["priority"] == cfg.priority


def test_autoscaler_only_leader_acts(kv_pair):
    from tpu_sandbox.serve.autoscale import (AutoscaleConfig,
                                             ReplicaAutoscaler)

    _, kv = kv_pair
    cfg = AutoscaleConfig(min_replicas=1, cooldown_s=0.0)
    leader = ReplicaAutoscaler(kv, ARGV, cfg=cfg, member_id="m0")
    follower = ReplicaAutoscaler(kv, ARGV, cfg=cfg, member_id="m1")
    assert leader.tick() is not None       # m0 wins the first election
    assert follower.tick() is None         # m1 observes, never acts
    assert len(leader.replica_jobs()) == 1


def test_autoscaler_hysteresis_resets_on_mixed_signal(kv_pair):
    from tpu_sandbox.serve.autoscale import (AutoscaleConfig,
                                             ReplicaAutoscaler)

    _, kv = kv_pair
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=3, hysteresis_ticks=2,
                          cooldown_s=0.0)
    a = ReplicaAutoscaler(kv, ARGV, cfg=cfg)
    a.tick()  # bootstrap
    _reports(kv, {"w0": 10.0})
    assert a.tick() is None
    _reports(kv, {"w0": 2.0})  # back inside the band: streak resets
    assert a.tick() is None
    _reports(kv, {"w0": 10.0})
    assert a.tick() is None    # streak restarted from zero
    ev = a.tick()
    assert ev and ev["action"] == "scale_up"


def test_autoscaler_prewarms_compile_cache(kv_pair, tmp_path):
    """Satellite: scale-ups point every replica at one shared XLA compile
    cache, and each event records whether the new replica finds it warm
    (deserialize executables) or cold (first compile pays full price)."""
    from tpu_sandbox.runtime.scheduler import JobSpec, k_spec
    from tpu_sandbox.serve.autoscale import (AutoscaleConfig,
                                             ReplicaAutoscaler)

    _, kv = kv_pair
    cache = tmp_path / "xla-cache"
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=2, hysteresis_ticks=1,
                          cooldown_s=0.0, compile_cache_dir=str(cache))
    a = ReplicaAutoscaler(kv, ARGV, cfg=cfg)
    ev = a.tick()  # bootstrap replica: nothing cached yet
    assert ev and ev["compile_cache"] == "cold"
    spec = JobSpec.from_json(kv.try_get(k_spec(ev["job_id"])).decode())
    assert spec.env["JAX_COMPILATION_CACHE_DIR"] == str(cache)
    # the bootstrap replica compiled and persisted its executables
    (cache / "xla_dump").write_bytes(b"cached executable")
    _reports(kv, {"w0": 10.0})
    ev = a.tick()  # load-driven scale-up reacts to a WARM cache
    assert ev and ev["action"] == "scale_up"
    assert ev["compile_cache"] == "warm"
    spec = JobSpec.from_json(kv.try_get(k_spec(ev["job_id"])).decode())
    assert spec.env["JAX_COMPILATION_CACHE_DIR"] == str(cache)
    # no cache dir configured -> events say so instead of guessing
    assert ReplicaAutoscaler(
        kv, ARGV, cfg=AutoscaleConfig(), member_id="m9",
    ).compile_cache_state() == "disabled"


# -- sampling (satellite: replay-exact requeue) ------------------------------


def test_sample_token_is_deterministic_and_top_k_bounded():
    from tpu_sandbox.serve.decode import sample_token

    rng = np.random.default_rng(0)
    logits = rng.normal(size=64).astype(np.float32)
    draws = {sample_token(logits, seed=7, step_index=3, temperature=0.8,
                          top_k=5) for _ in range(4)}
    assert len(draws) == 1  # same (seed, step) -> same token, always
    # different step indices decorrelate the stream
    seq = [sample_token(logits, seed=7, step_index=i, temperature=0.8)
           for i in range(32)]
    assert len(set(seq)) > 1
    # top_k=1 degenerates to argmax regardless of temperature
    assert sample_token(logits, seed=7, step_index=0, temperature=5.0,
                        top_k=1) == int(logits.argmax())


def test_sampled_request_replays_bitwise_after_requeue():
    """Kill-and-requeue a temperature/top-k request mid-decode (stub step):
    the replayed trajectory is identical because the sampler key folds the
    request seed with the decode-step index, both of which replay."""
    kw = dict(temperature=0.9, top_k=8, seed=42)
    ref = _engine()
    ref.submit(_req("s0", n=12, **kw))
    ref.run_until_idle()
    want = ref.results["s0"].tokens

    eng = _engine()
    eng.submit(_req("s0", n=12, **kw))
    for _ in range(5):
        eng.step()
    # replica death: everything in flight goes back to request form...
    reqs = eng.drain_to_requests()
    assert len(reqs) == 1 and reqs[0].temperature == 0.9
    # ...and replays from the original prompt on a fresh engine
    eng2 = _engine()
    eng2.submit(reqs[0])
    eng2.run_until_idle()
    assert eng2.results["s0"].tokens == want


# -- client: canary-share pinning before enqueue ------------------------------


def test_client_pins_canary_share_before_enqueue(kv_pair):
    from tpu_sandbox.deploy.registry import k_shares
    from tpu_sandbox.serve import replica as R
    from tpu_sandbox.serve.client import ServeClient

    _, kv = kv_pair
    # no live shares (the common case): one try_get, no pin written
    quiet = ServeClient(kv)
    quiet.submit("r0", [1, 2, 3], 2)
    assert kv.try_get(R.k_pin("r0")) is None
    # a live canary split with all weight on version 7: every submit
    # pins to 7 BEFORE the enqueue, so the first claimer sees it
    kv.set(k_shares(""), json.dumps(
        {"seq": 7, "shares": {"7": 1.0, "0": 0.0}}))
    client = ServeClient(kv, share_seed=42)
    client.submit("r1", [1, 2, 3], 2)
    assert int(kv.get(R.k_pin("r1"))) == 7


def test_client_share_draws_seeded_and_split(kv_pair):
    from tpu_sandbox.deploy.registry import k_shares
    from tpu_sandbox.serve import replica as R
    from tpu_sandbox.serve.client import ServeClient

    _, kv = kv_pair
    kv.set(k_shares(""), json.dumps(
        {"seq": 7, "shares": {"7": 0.5, "0": 0.5}}))

    def draw_sequence(seed, tag):
        c = ServeClient(kv, share_seed=seed)
        pins = []
        for i in range(8):
            rid = f"{tag}-{i}"
            c.submit(rid, [1, 2, 3], 2)
            pins.append(int(kv.get(R.k_pin(rid))))
        return pins

    a = draw_sequence(1234, "a")
    b = draw_sequence(1234, "b")
    assert a == b  # same seed -> same version sequence (replayable)
    assert set(a) == {0, 7}  # a 50/50 split actually splits in 8 draws


def test_client_fleet_view_reads_root_shares(kv_pair):
    from tpu_sandbox.deploy.registry import k_shares
    from tpu_sandbox.gateway.fleet import fleet_kv
    from tpu_sandbox.serve import replica as R
    from tpu_sandbox.serve.client import ServeClient

    _, kv = kv_pair
    # deploy keys live at the store ROOT keyed by fleet; the serve pin
    # lands inside the fleet namespace the client was built over
    kv.set(k_shares("chat"), json.dumps(
        {"seq": 3, "shares": {"3": 1.0}}))
    client = ServeClient(fleet_kv(kv, "chat"), share_seed=0)
    client.submit("r0", [1, 2, 3], 2)
    assert int(kv.get("fleet/chat/" + R.k_pin("r0"))) == 3
    assert kv.try_get(R.k_pin("r0")) is None  # nothing at the root
