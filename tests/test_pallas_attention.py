"""Flash-attention kernel vs the reference math (interpret mode on CPU).

Mirrors the test strategy used for the other Pallas kernel (test_aux's CE
checks): same call path as TPU, interpret=True, numerical parity against
ops.attention.causal_attention which is itself torch-verified via the
transformer tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.ops.attention import causal_attention
from tpu_sandbox.ops.pallas_attention import flash_attention, flash_attention_fn


def _rand_qkv(b=2, s=256, h=2, d=32, seed=0):
    rng = np.random.default_rng(seed)
    shape = (b, s, h, d)
    return tuple(
        jnp.asarray(rng.standard_normal(shape, dtype=np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = _rand_qkv()
    ref = causal_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_forward_unaligned_seq_and_headdim():
    # S=200 pads to 256, D=24 pads to the 128 lane tile
    q, k, v = _rand_qkv(s=200, d=24, seed=1)
    ref = causal_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    q, k, v = _rand_qkv(s=128, d=16, seed=2)
    w = jnp.asarray(
        np.random.default_rng(3).standard_normal(q.shape, dtype=np.float32)
    )

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       interpret=True) * w)

    def loss_ref(q, k, v):
        return jnp.sum(causal_attention(q, k, v, causal=causal) * w)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), rtol=5e-5, atol=5e-5,
            err_msg=f"grad d{name} mismatch",
        )


def test_gradients_unaligned_seq_and_headdim():
    """Backward through the padding path: S=200 pads to 256 (zero-cotangent
    padded rows), D=24 pads to the 128-lane tile."""
    q, k, v = _rand_qkv(s=200, d=24, seed=5)
    w = jnp.asarray(
        np.random.default_rng(6).standard_normal(q.shape, dtype=np.float32)
    )

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, interpret=True) * w)

    def loss_ref(q, k, v):
        return jnp.sum(causal_attention(q, k, v) * w)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), rtol=5e-5, atol=5e-5,
            err_msg=f"grad d{name} mismatch",
        )


def test_pallas_bwd_matches_jnp_blockwise_bwd():
    """The Pallas backward kernels against the jnp scan backward they
    replaced (kept as the O(S·block) reference implementation)."""
    from tpu_sandbox.ops.pallas_attention import (
        _blockwise_bwd,
        _flash_bwd,
        _flash_fwd,
    )

    rng = np.random.default_rng(7)
    b, h, s, d = 2, 2, 256, 128
    q, k, v, g = (
        jnp.asarray(rng.standard_normal((b, h, s, d), dtype=np.float32))
        for _ in range(4)
    )
    scale = 1.0 / d**0.5
    out, lse = _flash_fwd(q, k, v, scale, True, 128, 128, True, s)
    ref = _blockwise_bwd(q, k, v, out, lse, g, scale, True, 128, s)
    delta = jnp.sum(g * out, axis=-1)
    got = _flash_bwd(q, k, v, delta, lse, g, scale, True, 128, 128, True, s)
    for gf, gr, name in zip(got, ref, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), rtol=2e-5, atol=2e-5,
            err_msg=f"{name} mismatch",
        )


def test_transformer_with_flash_attention():
    from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(vocab_size=32, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, max_len=128)
    rng = np.random.default_rng(4)
    tokens = jnp.asarray(rng.integers(0, 32, size=(2, 128)), jnp.int32)

    ref_model = TransformerLM(cfg)
    variables = ref_model.init(jax.random.key(0), tokens)
    ref_logits = ref_model.apply(variables, tokens)

    flash_model = TransformerLM(cfg, attention_fn=flash_attention_fn(
        interpret=True))
    logits = flash_model.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)
