"""Flight recorder tier-1 suite: recorder semantics, the metrics
registry, clock-offset calibration, Chrome export, postmortem windows —
and THE acceptance test: end-to-end trace completeness through a live
2-replica gateway fleet (every non-shed request yields one connected
submit→route→enqueue→claim→admit→decode→verdict chain with exactly one
root; door sheds terminate in a ``door:infeasible`` span).

Everything runs in-process with the stub decode step from
test_gateway.py — real sockets, real KV, no jax compiles. The recorder
is process-global, so the in-process "fleet" writes one log file; the
collector treats that as the degenerate single-process merge, which is
exactly what the chain checks exercise (causality is carried by span
ids, not by which file a record landed in).
"""

import json
import time

import pytest

from tpu_sandbox.obs import (ENV_TRACE_DIR, MetricsRegistry, Recorder,
                             TraceContext, collect, get_recorder,
                             reset_recorder)
from tpu_sandbox.obs.record import ENV_PROC_NAME

from tests.test_gateway import (_gateway, _pumping, _wait_for_report,
                                _worker, kv_pair)  # noqa: F401 (fixture)


@pytest.fixture
def traced(tmp_path, monkeypatch):
    """Route the process-global recorder into a temp dir for the test,
    and restore the (disabled) recorder afterwards."""
    monkeypatch.setenv(ENV_TRACE_DIR, str(tmp_path))
    monkeypatch.setenv(ENV_PROC_NAME, "test")
    reset_recorder()
    yield str(tmp_path)
    reset_recorder()


# -- recorder semantics -------------------------------------------------------


def test_disabled_recorder_passes_context_through():
    rec = Recorder(None)
    parent = TraceContext("t1", "s1")
    with rec.span("outer", parent=parent) as sp:
        # a dark process must not sever the chain: children still see
        # the upstream context
        assert sp.ctx == parent
    assert rec.complete("x", time.monotonic(), parent=parent) == parent
    assert rec.instant("x", parent=parent) == parent
    assert rec.complete("x", time.monotonic()) is None
    assert rec.stats() == {"events": 0, "dropped": 0}


def test_recorder_emits_nested_spans(tmp_path):
    path = str(tmp_path / "t.jsonl")
    rec = Recorder(path, proc="unit", flush_every=1)
    with rec.span("outer", args={"rid": "r0"}) as outer:
        with rec.span("inner", parent=outer.ctx):
            pass
    rec.instant("mark", parent=outer.ctx)
    rec.close()
    records = collect.read_log(path)
    by_ph = {}
    for r in records:
        by_ph.setdefault(r["ph"], []).append(r)
    assert len(by_ph["P"]) == 1 and len(by_ph["X"]) == 2
    inner, outer_rec = by_ph["X"]  # inner closes first
    assert (inner["name"], outer_rec["name"]) == ("inner", "outer")
    assert inner["trace"] == outer_rec["trace"]
    assert inner["parent"] == outer_rec["span"]
    assert by_ph["i"][0]["parent"] == outer_rec["span"]
    assert outer_rec["parent"] is None
    assert all(r["proc"] == "unit" and r["pid"] > 0 for r in records)
    assert outer_rec["dur"] >= inner["dur"] >= 0.0


def test_trace_context_wire_roundtrip_is_tolerant():
    ctx = TraceContext("abc", "1.2")
    assert TraceContext.from_wire(ctx.to_wire()) == ctx
    assert TraceContext.from_wire(ctx) is ctx
    assert TraceContext.from_wire(None) is None
    # malformed wire dicts read as "no context", never raise
    assert TraceContext.from_wire({"t": "abc"}) is None
    assert TraceContext.from_wire("garbage") is None


def test_backpressure_drops_newest_and_counts(tmp_path):
    path = str(tmp_path / "bp.jsonl")
    # manual flush mode: the buffer is the only sink until flush()
    rec = Recorder(path, proc="bp", flush_every=0, max_buffered=8)
    for i in range(20):
        rec.instant(f"e{i}")
    # preamble was force-flushed at open; 8 instants buffered, 12 dropped
    assert rec.stats() == {"events": 9, "dropped": 12}
    rec.close()
    assert len(collect.read_log(path)) == 9


# -- metrics registry ---------------------------------------------------------


def test_metrics_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.counter("req").inc()
    reg.counter("req").inc(2)
    reg.gauge("depth").set(7)
    h = reg.histogram("lat")
    for v in range(1, 101):
        h.observe(float(v))
    snap = reg.snapshot()
    assert snap["counters"]["req"] == 3
    assert snap["gauges"]["depth"] == 7
    lat = snap["histograms"]["lat"]
    assert lat["count"] == 100 and lat["min"] == 1.0 and lat["max"] == 100.0
    assert lat["p50"] <= lat["p90"] <= lat["p99"] <= 100.0
    assert 40.0 <= lat["p50"] <= 60.0
    # same name returns the same instrument; reset drops everything
    assert reg.counter("req").value == 3
    reg.reset()
    assert reg.snapshot() == {"counters": {}, "gauges": {},
                              "histograms": {}}


def test_metrics_registry_label_series_are_distinct_and_stable():
    from tpu_sandbox.obs.metrics import series_key

    assert series_key("engine.shed", None) == "engine.shed"
    # label keys sort, so the same label SET is always the same series
    assert series_key("engine.shed", {"reason": "deadline", "a": "b"}) == \
        "engine.shed{a=b,reason=deadline}"
    reg = MetricsRegistry()
    reg.counter("engine.shed", labels={"reason": "deadline"}).inc()
    reg.counter("engine.shed", labels={"reason": "door"}).inc(2)
    reg.counter("engine.shed", labels={"reason": "deadline"}).inc()
    snap = reg.snapshot()["counters"]
    assert snap["engine.shed{reason=deadline}"] == 2
    assert snap["engine.shed{reason=door}"] == 2
    assert "engine.shed" not in snap  # the bare name was never minted


# -- clock calibration / merge ------------------------------------------------


def _cal(seq, mono, wall, **kw):
    return dict({"ph": "C", "seq": seq, "mono": mono, "rtt": 0.001,
                 "wall": wall}, **kw)


def _span(name, ts, trace, span, parent=None, dur=0.01, **kw):
    return dict({"ph": "X", "name": name, "ts": ts, "dur": dur,
                 "trace": trace, "span": span, "parent": parent,
                 "args": {}}, **kw)


def test_clock_offsets_repair_skewed_wall_clocks():
    # proc a: mono ~10, wall = mono + 1000 (the true offset)
    # proc b: mono ~20, wall = mono + 980 — its wall clock runs 10 s
    # behind, so the wall anchor alone would order b's seq-2 point
    # BEFORE a's seq-1 point. The sequencer repair must bump b forward.
    logs = {
        "a/1": [_cal(1, 10.0, 1010.0), _cal(3, 10.1, 1010.1),
                _span("first", 10.02, "T", "a.1")],
        "b/2": [_cal(2, 20.0, 1000.0), _cal(4, 20.1, 1000.1),
                _span("second", 20.05, "T", "b.1", parent="a.1")],
    }
    offsets = collect.clock_offsets(logs)
    assert offsets["a/1"] == pytest.approx(1000.0)
    # repaired: b's seq-2 point may not precede a's seq-1 point
    assert offsets["b/2"] == pytest.approx(990.0)
    merged = collect.merge(logs, offsets)
    assert [r["name"] for r in merged] == ["first", "second"]
    assert merged[0]["uts"] <= merged[1]["uts"]
    # and the chain across the two processes validates
    chk = collect.chain_check(merged)
    assert chk["connected"] and chk["roots"] == 1


def test_calibrate_against_live_kv_sequencer(tmp_path):
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    server = KVServer()
    kv = KVClient(port=server.port)
    try:
        path = str(tmp_path / "cal.jsonl")
        rec = Recorder(path, proc="cal")
        last = rec.calibrate(kv, rounds=3)
        rec.close()
        cals = [r for r in collect.read_log(path) if r["ph"] == "C"]
        assert len(cals) == 3
        seqs = [c["seq"] for c in cals]
        assert seqs == sorted(seqs) and seqs[-1] == last
        assert all(c["rtt"] >= 0 for c in cals)
    finally:
        kv.close()
        server.stop()
    assert Recorder(None).calibrate(None) == 0  # disabled: no kv traffic


def test_chrome_trace_export_is_valid(tmp_path):
    path = str(tmp_path / "c.jsonl")
    rec = Recorder(path, proc="chrome")
    with rec.span("req", args={"rid": "r1"}) as sp:
        rec.instant("mark", parent=sp.ctx)
    rec.close()
    merged = collect.merge(collect.load_dir(str(tmp_path)))
    doc = collect.to_chrome_trace(merged)
    # survives a JSON round trip (what Perfetto actually loads)
    doc = json.loads(json.dumps(doc))
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert len(meta) == 1 and meta[0]["name"] == "process_name"
    spans = [e for e in evs if e["ph"] == "X"]
    instants = [e for e in evs if e["ph"] == "i"]
    assert len(spans) == 1 and len(instants) == 1
    assert spans[0]["ts"] >= 0 and spans[0]["dur"] >= 0
    assert isinstance(spans[0]["pid"], int)
    assert instants[0]["s"] == "p"
    assert spans[0]["args"]["trace"] == instants[0]["args"]["trace"]


def test_clock_offsets_fall_back_to_preamble_without_calibration():
    # headless run: nobody calibrated against the KV sequencer, so only
    # the "P" preambles anchor each process's monotonic clock
    logs = {
        "a/1": [{"ph": "P", "mono": 10.0, "wall": 1010.0},
                _span("first", 10.02, "T", "a.1")],
        "b/2": [{"ph": "P", "mono": 20.0, "wall": 2020.0},
                _span("second", 20.05, "T", "b.1", parent="a.1")],
    }
    offsets = collect.clock_offsets(logs)
    assert offsets["a/1"] == pytest.approx(1000.0)
    assert offsets["b/2"] == pytest.approx(2000.0)
    merged = collect.merge(logs)
    assert [r["name"] for r in merged] == ["first", "second"]


def test_clock_offsets_median_rides_out_wall_clock_step():
    # NTP steps the wall clock 100 s forward mid-run: the stepped
    # calibration point is an outlier the median anchor must shrug off
    logs = {
        "a/1": [_cal(1, 10.0, 1010.0), _cal(2, 10.1, 1010.1),
                _cal(3, 10.2, 1110.2)],
    }
    assert collect.clock_offsets(logs)["a/1"] == pytest.approx(1000.0)


def test_clock_offsets_single_process_defaults_to_zero():
    # no C and no P records at all (truncated log): offset 0.0, and the
    # degenerate single-process merge still works
    logs = {"solo/1": [_span("only", 5.0, "T", "s.1")]}
    assert collect.clock_offsets(logs) == {"solo/1": 0.0}
    assert collect.merge(logs)[0]["uts"] == pytest.approx(5.0)


def test_metric_samples_round_trip_as_chrome_counter_tracks(tmp_path):
    path = str(tmp_path / "m.jsonl")
    rec = Recorder(path, proc="meter", flush_every=1)
    rec.metric("sched.queue.depth", 3.0)
    rec.metric("sched.queue.depth", 5.0)
    rec.close()
    merged = collect.merge(collect.load_dir(str(tmp_path)))
    assert [r["value"] for r in merged if r["ph"] == "m"] == [3.0, 5.0]
    doc = json.loads(json.dumps(collect.to_chrome_trace(merged)))
    counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert len(counters) == 2
    assert all(c["name"] == "sched.queue.depth" for c in counters)
    # Perfetto draws the track from args.value at each ts
    assert [c["args"]["value"] for c in counters] == [3.0, 5.0]
    assert counters[0]["ts"] <= counters[1]["ts"]
    assert all(isinstance(c["args"]["value"], float) for c in counters)


def test_last_window_measures_from_last_record_not_now():
    merged = [
        {"ph": "i", "name": "old", "uts": 100.0, "args": {}},
        {"ph": "i", "name": "kill", "uts": 200.0, "args": {"agent": 1}},
        {"ph": "i", "name": "requeue", "uts": 201.5, "args": {}},
    ]
    tail = collect.last_window(merged, 5.0)
    assert [r["name"] for r in tail] == ["kill", "requeue"]
    text = collect.format_timeline(tail)
    assert "! [?] kill  agent=1" in text
    assert text.splitlines()[0].startswith("+   0.000s")
    assert collect.format_timeline([]) == "(no records in window)"


# -- OP_METRICS scrape --------------------------------------------------------


def test_gateway_metrics_scrape_over_socket(kv_pair, traced):
    from tpu_sandbox.gateway.client import GatewayClient
    from tpu_sandbox.obs import get_registry

    _, kv, clone = kv_pair
    w = _worker(clone(), tag="w0")
    with _gateway(kv) as gw, _pumping(w):
        _wait_for_report(kv, "w0")
        with GatewayClient(gw.port) as client:
            assert client.submit("m0", [1, 2, 3], 2) is True
            assert client.result("m0", timeout=30.0)["verdict"] == "ok"
            body = client.metrics()
    snap = body["registry"]
    assert snap == get_registry().snapshot()
    # the gateway's own recorder stats plus each replica's, scraped from
    # the TTL load reports — a silently-dropping recorder is visible
    assert body["recorder"]["events"] > 0
    assert body["recorder"]["dropped"] == 0
    assert "default/w0" in body["replica_recorders"]
    assert set(body["replica_recorders"]["default/w0"]) == \
        {"events", "dropped"}
    # the fleet-wide drop total the recorder_drops health rule keys on
    assert body["dropped_events"] == body["recorder"]["dropped"] + \
        body["replica_recorders"]["default/w0"]["dropped"]


# -- THE acceptance test: end-to-end trace completeness -----------------------

#: the full causal chain every successfully served request must leave
FULL_CHAIN = {"submit", "route", "enqueue", "claim", "admit", "decode",
              "verdict"}


def test_trace_completeness_two_replica_fleet(kv_pair, traced):
    from tpu_sandbox.gateway.client import GatewayClient

    _, kv, clone = kv_pair
    w0 = _worker(clone(), tag="w0")
    w1 = _worker(clone(), tag="w1")
    with _gateway(kv) as gw, _pumping(w0, w1):
        _wait_for_report(kv, "w0")
        _wait_for_report(kv, "w1")
        get_recorder().calibrate(kv, rounds=3)
        with GatewayClient(gw.port) as client:
            rids = [f"r{i}" for i in range(10)]
            for i, rid in enumerate(rids):
                assert client.submit(rid, [i + 1, i + 2, i + 3], 3)
            for rid in rids:
                assert client.result(rid, timeout=30.0)["verdict"] == "ok"
            # one request the feasibility door must refuse: no fleet can
            # finish anything in a nanosecond
            assert client.submit("doomed", [9, 9, 9], 3,
                                 deadline_s=1e-9) is False
    get_recorder().flush()

    merged = collect.load_merged(traced)
    chains = collect.trace_chains(merged)
    full, shed = 0, 0
    for tid, records in chains.items():
        chk = collect.chain_check(records)
        # exactly one root, and it is the client's submit span
        assert chk["connected"], (tid, chk)
        assert chk["root_names"] == ["submit"], (tid, chk)
        names = set(chk["names"])
        if any(n.startswith("door:") for n in names):
            shed += 1
            assert "door:infeasible" in names, names
            # a door shed never reaches the engine
            assert not names & {"claim", "admit", "decode"}, names
        elif FULL_CHAIN <= names:
            full += 1
    assert full >= len(rids), (full, {t: c["names"] for t, c in
                                      ((t, collect.chain_check(r))
                                       for t, r in chains.items())})
    assert shed == 1

    # the merged output is valid Chrome trace-event JSON
    doc = json.loads(json.dumps(collect.to_chrome_trace(merged)))
    assert len(doc["traceEvents"]) > len(merged)

    # and the waterfall renders a served request's life
    rows = collect.request_waterfall(merged, rid="r0")
    assert rows and rows[0]["name"] == "submit"
    text = collect.format_waterfall(rows)
    assert "submit" in text and "decode" in text
