"""Canned-HLO coverage for the pure-text analyzers in tools/.

The `-done` opcode bug class: async collectives appear twice in scheduled
HLO (`all-reduce-start` + `all-reduce-done`); counting both doubles the
traffic number, counting neither drops it. These tests pin the parsing
contracts of ``hlo_traffic.collective_bytes`` (per-opcode bucketing) and
``hlo_schedule.schedule_report`` (monolithic baseline vs overlapped
schedule) against hand-written modules where every byte is computable by
eye — no compiles, CPU-only.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

from hlo_schedule import schedule_report  # noqa: E402
from hlo_traffic import collective_bytes, shape_bytes  # noqa: E402

# ---------------------------------------------------------------------------
# shape_bytes: TPU tiling padding
# ---------------------------------------------------------------------------


def test_shape_bytes_unpadded_and_padded():
    # no layout: logical bytes
    assert shape_bytes("f32[256,128]") == 256 * 128 * 4
    # T(8,128) tiling pads the two minor physical dims to (8, 128) for f32
    assert shape_bytes("f32[4,100]{1,0:T(8,128)}") == 8 * 128 * 4
    # bf16 second-level tiling pads sublanes to 16
    assert shape_bytes("bf16[4,100]{1,0:T(8,128)(2,1)}") == 16 * 128 * 2
    # tuple shapes sum element-wise; unknown dtypes (token) are skipped
    assert shape_bytes("(f32[16], s32[4])") == 16 * 4 + 4 * 4


# ---------------------------------------------------------------------------
# collective_bytes: per-opcode bucketing + the -start/-done split
# ---------------------------------------------------------------------------

_TRAFFIC_HLO = """\
HloModule mod, is_scheduled=true

ENTRY %main.1 (p0: f32[256,128]) -> f32[256,128] {
  %p0 = f32[256,128] parameter(0)
  %ar.0 = f32[256,128] all-reduce(f32[256,128] %p0), to_apply=%add
  %ags.0 = f32[64,128] all-gather-start(f32[64,128] %p0), dimensions={0}
  %agd.0 = f32[256,128] all-gather-done(f32[256,128] %ags.0)
  %cp.0 = f32[16,128] collective-permute(f32[16,128] %p0)
  ROOT %add.0 = f32[256,128] add(f32[256,128] %ar.0, f32[256,128] %agd.0)
}
"""


def test_collective_bytes_per_opcode():
    out = collective_bytes(_TRAFFIC_HLO)
    # all-reduce counts its full operand
    assert out["by_opcode"]["all-reduce"] == 256 * 128 * 4
    # the async all-gather counts ONCE, from the -start operand (the local
    # shard); the -done half carries no payload and must be skipped
    assert out["by_opcode"]["all-gather"] == 64 * 128 * 4
    assert out["by_opcode"]["collective-permute"] == 16 * 128 * 4
    assert out["total"] == sum(out["by_opcode"].values())
    # nothing leaked in under the -done spelling
    assert "all-gather-done" not in out["by_opcode"]


def test_collective_bytes_ignores_non_collectives():
    assert collective_bytes("""\
ENTRY %m (p0: f32[8]) -> f32[8] {
  %p0 = f32[8] parameter(0)
  ROOT %c = f32[8] copy(f32[8] %p0)
}
""") == {"total": 0, "by_opcode": {}}


# ---------------------------------------------------------------------------
# schedule_report: monolithic baseline vs overlapped schedule
# ---------------------------------------------------------------------------

_MONO_HLO = """\
HloModule train_step, is_scheduled=true

%add.clone (x.1: f32[], y.1: f32[]) -> f32[] {
  %x.1 = f32[] parameter(0)
  %y.1 = f32[] parameter(1)
  ROOT %add.9 = f32[] add(f32[] %x.1, f32[] %y.1)
}

ENTRY %main.42 (p0: f32[256,128]) -> f32[256,128] {
  %p0 = f32[256,128] parameter(0)
  %dot.fwd = f32[256,128] dot(f32[256,128] %p0, f32[256,128] %p0), metadata={op_name="jit(train_step)/jvp(loss)/dot_general"}
  %fusion.bwd = f32[256,128] fusion(f32[256,128] %dot.fwd), kind=kLoop, metadata={op_name="jit(train_step)/transpose(jvp(loss))/mul"}
  ROOT %all-reduce.0 = f32[256,128] all-reduce(f32[256,128] %fusion.bwd), replica_groups={{0,1,2,3}}, to_apply=%add.clone
}
"""

_OVERLAP_HLO = """\
HloModule train_step, is_scheduled=true

ENTRY %main.42 (p0: f32[256,128]) -> f32[256,128] {
  %p0 = f32[256,128] parameter(0)
  %dot.fwd = f32[256,128] dot(f32[256,128] %p0, f32[256,128] %p0), metadata={op_name="jit(train_step)/jvp(loss)/dot_general"}
  %ar-start.0 = f32[100,128] all-reduce-start(f32[100,128] %dot.fwd), to_apply=%add.clone
  %fusion.bwd1 = f32[256,128] fusion(f32[256,128] %dot.fwd), kind=kLoop, metadata={op_name="jit(train_step)/transpose(jvp(loss))/mul"}
  %ar-done.0 = f32[100,128] all-reduce-done(f32[100,128] %ar-start.0)
  %all-reduce.1 = f32[50,128] all-reduce(f32[50,128] %fusion.bwd1), to_apply=%add.clone
  ROOT %fusion.bwd2 = f32[256,128] fusion(f32[256,128] %fusion.bwd1), kind=kLoop, metadata={op_name="jit(train_step)/transpose(jvp(loss))/add"}
}
"""


def test_schedule_report_monolithic_baseline():
    """The shape the bucketing exists to kill: one all-reduce scheduled
    after the last backward compute op — fully exposed."""
    rep = schedule_report(_MONO_HLO)
    assert rep["collective_count"] == 1
    assert rep["sync_collectives"] == 1
    assert rep["all_reduce_issues_before_last_bwd_compute"] == 0
    assert rep["comm_bytes_exposed"] == 256 * 128 * 4
    assert rep["comm_bytes_overlapped"] == 0
    assert rep["exposed_comm_fraction"] == 1.0
    assert rep["last_bwd_compute_op"] == "fusion.bwd"


def test_schedule_report_overlapped_schedule():
    """Async pair with compute between start/done + a sync collective
    issued before the last backward op: everything overlaps."""
    rep = schedule_report(_OVERLAP_HLO)
    assert rep["collective_count"] == 2
    assert rep["async_pairs"] == 1
    assert rep["sync_collectives"] == 1
    # both the -start and the sync form issue before fusion.bwd2
    assert rep["all_reduce_issues_before_last_bwd_compute"] == 2
    pair = [c for c in rep["collectives"] if c["form"] == "async"][0]
    assert pair["compute_ops_between"] == 1 and pair["overlapped"]
    assert rep["comm_bytes_exposed"] == 0
    assert rep["exposed_comm_fraction"] == 0.0


def test_schedule_report_orphan_start_counts_exposed():
    """A -start whose -done never appears must count as exposed bytes,
    not vanish (the dual of the -done double-count bug)."""
    orphan = _OVERLAP_HLO.replace(
        "  %ar-done.0 = f32[100,128] all-reduce-done"
        "(f32[100,128] %ar-start.0)\n", "")
    rep = schedule_report(orphan)
    assert rep["collective_count"] == 2
    exposed = [c for c in rep["collectives"] if not c["overlapped"]]
    assert len(exposed) == 1
    assert exposed[0]["bytes"] == 100 * 128 * 4
    assert rep["comm_bytes_exposed"] == 100 * 128 * 4
