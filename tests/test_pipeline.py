"""Pipeline-parallel tests: params split/merge roundtrip, pipelined step ==
single-device step, and learning over ticks — on a ('data','pipe') mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
from tpu_sandbox.ops.losses import cross_entropy_loss
from tpu_sandbox.parallel.pipeline import (
    PipelineParallel,
    merge_transformer_params,
    split_transformer_params,
)
from tpu_sandbox.runtime.mesh import make_mesh

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=4, d_ff=64, max_len=64
)


def lm_batch(b=8, s=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, CFG.vocab_size, size=(b, s)).astype(np.int32)
    targets = ((tokens + 7) % CFG.vocab_size).astype(np.int32)
    return tokens, targets


@pytest.fixture(scope="module")
def mesh_dp_pp():
    return make_mesh({"data": 2, "pipe": 4})


def test_split_merge_roundtrip():
    model = TransformerLM(CFG)
    tokens, _ = lm_batch()
    params = model.init(jax.random.key(0), jnp.asarray(tokens))["params"]
    pre, stacked, post = split_transformer_params(params, 4)
    assert jax.tree.leaves(stacked)[0].shape[0] == 4
    merged = merge_transformer_params(pre, stacked, post)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, merged,
    )
    with pytest.raises(ValueError, match="divisible"):
        split_transformer_params(params, 3)


def assert_matches_dense_reference(pp, cfg, tokens, targets, tx, *,
                                   loss_rtol=1e-5, param_atol=2e-5,
                                   state=None):
    """One pp.train_step from fresh init must reproduce the single-device
    dense-attention reference step: same loss, same updated params (merged
    back through merged_params). Pass ``state`` to reuse an already-built
    init (it must be unsharded or shardable by pp.shard_state)."""
    if state is None:
        state = pp.init_state(jax.random.key(0), jnp.asarray(tokens))
    model = TransformerLM(cfg)  # single-device reference, SAME init params
    flat_params = pp.merged_params(state)

    def ref_loss(params):
        logits = model.apply({"params": params}, jnp.asarray(tokens))
        return cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]), jnp.asarray(targets).reshape(-1)
        )

    ref_loss_val, ref_grads = jax.value_and_grad(ref_loss)(
        jax.tree.map(jnp.asarray, flat_params)
    )
    ref_params = optax.apply_updates(
        jax.tree.map(jnp.asarray, flat_params),
        tx.update(ref_grads, tx.init(flat_params), flat_params)[0],
    )

    new_state, loss = pp.train_step(
        pp.shard_state(state), *pp.shard_batch(tokens, targets)
    )
    np.testing.assert_allclose(float(loss), float(ref_loss_val), rtol=loss_rtol)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=param_atol
        ),
        pp.merged_params(new_state), jax.tree.map(np.asarray, ref_params),
    )


def test_pipeline_step_matches_single_device(mesh_dp_pp):
    tx = optax.sgd(0.1)
    pp = PipelineParallel(CFG, tx, mesh_dp_pp, microbatches=2, donate=False)
    tokens, targets = lm_batch()
    assert_matches_dense_reference(pp, CFG, tokens, targets, tx)


def test_pipeline_stage_params_are_sharded(mesh_dp_pp):
    pp = PipelineParallel(CFG, optax.sgd(0.1), mesh_dp_pp, microbatches=2, donate=False)
    tokens, _ = lm_batch()
    state = pp.shard_state(pp.init_state(jax.random.key(0), jnp.asarray(tokens)))
    leaf = jax.tree.leaves(state.params["stages"])[0]
    from jax.sharding import PartitionSpec as P

    assert leaf.sharding.spec == P("pipe")
    assert leaf.shape[0] == 4  # one stage row per pipe rank


def test_pipeline_training_learns(mesh_dp_pp):
    tx = optax.adam(1e-2)
    pp = PipelineParallel(CFG, tx, mesh_dp_pp, microbatches=2, donate=False)
    tokens, targets = lm_batch(b=8)
    state = pp.shard_state(pp.init_state(jax.random.key(1), jnp.asarray(tokens)))
    batch = pp.shard_batch(tokens, targets)
    losses = []
    for _ in range(25):
        state, loss = pp.train_step(state, *batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_pipeline_tp_stages_match_single_device():
    """3-axis data x model x pipe mesh: Megatron TP inside each stage must
    reproduce the single-device step (loss and updated params)."""
    mesh = make_mesh({"data": 2, "model": 2, "pipe": 2})
    tx = optax.sgd(0.1)
    pp = PipelineParallel(
        CFG, tx, mesh, microbatches=2, model_axis="model", donate=False
    )
    tokens, targets = lm_batch()
    state = pp.shard_state(
        pp.init_state(jax.random.key(0), jnp.asarray(tokens))
    )
    qkv = state.params["stages"]["attn"]["qkv"]["kernel"]
    from jax.sharding import PartitionSpec as P

    # leaf is [stage, chunk, layer, d_model, 3, H, hd]: heads dim sharded
    assert qkv.sharding.spec == P("pipe", None, None, None, None, "model")

    assert_matches_dense_reference(pp, CFG, tokens, targets, tx, state=state)


@pytest.mark.parametrize("chunks", [2, 4])
def test_circular_schedule_matches_single_device(chunks):
    """circular_chunks=v: layers round-robin over stages, microbatches ring
    v times; must still reproduce the single-device step exactly. n_layers=4
    over 2 stages x v chunks needs a deeper config for v=4."""
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2 * chunks * 1,
        d_ff=64, max_len=64,
    )
    mesh = make_mesh({"data": 4, "pipe": 2})
    tx = optax.sgd(0.1)
    pp = PipelineParallel(cfg, tx, mesh, microbatches=2,
                          circular_chunks=chunks, donate=False)
    assert pp.bubble_fraction() == pytest.approx(1 / (2 * chunks + 1))
    tokens, targets = lm_batch()
    assert_matches_dense_reference(pp, cfg, tokens, targets, tx)


@pytest.mark.slow  # two full pipeline compiles for a design-property
# receipt that only moves when stage partitioning changes; the 4d parity
# test keeps pipeline correctness in tier-1
def test_per_stage_flops_do_not_scale_with_n_stages():
    """VERDICT r01 weak #3's done-criterion, checked by XLA's own cost
    analysis: the cond-gated embed/head means a device's compiled FLOPs for
    one train step stay flat as stages are added (same model, same local
    batch) — the old design's full-batch embed+head on every stage made
    them scale ~linearly."""

    def step_flops(n_pipe):
        cfg = TransformerConfig(vocab_size=512, d_model=64, n_heads=2,
                                n_layers=4, d_ff=128, max_len=32)
        mesh = make_mesh({"data": 2, "pipe": n_pipe},
                         devices=jax.devices()[: 2 * n_pipe])
        pp = PipelineParallel(cfg, optax.sgd(0.1), mesh, microbatches=2,
                              donate=False)
        tokens = np.zeros((8, 16), np.int32)
        state = pp.shard_state(
            pp.init_state(jax.random.key(0), jnp.asarray(tokens))
        )
        args = (state, *pp.shard_batch(tokens, tokens))
        cost = pp._compile_for(state).lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return (cost or {}).get("flops")

    f2, f4 = step_flops(2), step_flops(4)
    if not (f2 and f4):
        pytest.skip("backend exposes no cost analysis")
    assert f4 / f2 < 1.3, (f2, f4)  # old design: ~2x


def test_circular_validates():
    mesh = make_mesh({"data": 2, "pipe": 4})
    with pytest.raises(ValueError, match="divisible into"):
        PipelineParallel(CFG, optax.sgd(0.1), mesh, microbatches=4,
                         circular_chunks=3)
    with pytest.raises(ValueError, match="circular schedule needs"):
        PipelineParallel(
            TransformerConfig(n_layers=8), optax.sgd(0.1), mesh,
            microbatches=2, circular_chunks=2,
        )


@pytest.mark.parametrize("model_axis", [None, "model"])
def test_pipeline_flash_matches_dense_reference(model_axis):
    """VERDICT r02 weak #4: attention_fn plumbs through to plain AND
    tensor-parallel stages. With the flash kernel injected (interpret mode
    on CPU, same call path as TPU) the pipelined step must reproduce the
    dense single-device step — flash==dense numerics are already pinned by
    test_pallas_attention; this pins the plumbing."""
    from tpu_sandbox.ops.pallas_attention import flash_attention_fn

    mesh = (make_mesh({"data": 2, "model": 2, "pipe": 2}) if model_axis
            else make_mesh({"data": 2, "pipe": 4}))
    tx = optax.sgd(0.1)
    pp = PipelineParallel(
        CFG, tx, mesh, microbatches=2, model_axis=model_axis, donate=False,
        attention_fn=flash_attention_fn(interpret=True),
    )
    tokens, targets = lm_batch()
    assert_matches_dense_reference(pp, CFG, tokens, targets, tx,
                                   loss_rtol=1e-4, param_atol=5e-5)


@pytest.mark.parametrize("seq_attn", ["ring", "flash_ring"])
def test_pipeline_sp_matches_dense_reference(seq_attn):
    """Sequence parallelism INSIDE pipeline stages (dp x pp x sp): ring
    attention over 'sp' mixes positions across shards while activations
    ride the pipe as [mb, S/sp, D] slices; embedding offsets global
    positions; loss/grads pmean over 'sp'. Must reproduce the dense
    single-device step exactly."""
    mesh = make_mesh({"data": 2, "pipe": 2, "sp": 2})
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=64)
    tx = optax.sgd(0.1)
    pp = PipelineParallel(cfg, tx, mesh, microbatches=2, donate=False,
                          seq_axis="sp", seq_attn=seq_attn)
    tokens, targets = lm_batch()
    assert_matches_dense_reference(pp, cfg, tokens, targets, tx,
                                   loss_rtol=1e-4, param_atol=5e-5)


def test_pipeline_4d_matches_dense_reference():
    """The full composition — data x model x pipe x sp on one mesh
    (Megatron TP inside stages AND ring attention over the sequence) —
    reproduces the dense single-device step. Needs 16 virtual devices, so
    it runs in a subprocess (the suite's conftest pins 8)."""
    import subprocess
    import sys

    script = """
import os
os.environ['XLA_FLAGS'] = ' '.join(
    [f for f in os.environ.get('XLA_FLAGS', '').split()
     if 'xla_force_host_platform_device_count' not in f]
    + ['--xla_force_host_platform_device_count=16'])
import jax
jax.config.update('jax_platforms', 'cpu')
try:
    jax.config.update('jax_num_cpu_devices', 16)
except AttributeError:
    pass  # older jax: the XLA_FLAGS env above already sizes the host platform
import jax.numpy as jnp, numpy as np, optax
from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
from tpu_sandbox.ops.losses import cross_entropy_loss
from tpu_sandbox.parallel.pipeline import PipelineParallel
from tpu_sandbox.runtime.mesh import make_mesh

cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_len=64)
mesh = make_mesh({'data': 2, 'model': 2, 'pipe': 2, 'sp': 2})
tx = optax.sgd(0.1)
pp = PipelineParallel(cfg, tx, mesh, microbatches=2, donate=False,
                      model_axis='model', seq_axis='sp')
rng = np.random.default_rng(0)
tokens = rng.integers(0, 64, size=(8, 16)).astype(np.int32)
targets = ((tokens + 7) % 64).astype(np.int32)
state = pp.init_state(jax.random.key(0), jnp.asarray(tokens))
model = TransformerLM(cfg)
flat = pp.merged_params(state)
def ref_loss(params):
    logits = model.apply({'params': params}, jnp.asarray(tokens))
    return cross_entropy_loss(logits.reshape(-1, 64),
                              jnp.asarray(targets).reshape(-1))
ref_val, ref_grads = jax.value_and_grad(ref_loss)(
    jax.tree.map(jnp.asarray, flat))
ref_params = optax.apply_updates(
    jax.tree.map(jnp.asarray, flat),
    tx.update(ref_grads, tx.init(flat), flat)[0])
new_state, loss = pp.train_step(
    pp.shard_state(state), *pp.shard_batch(tokens, targets))
np.testing.assert_allclose(float(loss), float(ref_val), rtol=1e-5)
jax.tree.map(
    lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=3e-5),
    pp.merged_params(new_state), jax.tree.map(np.asarray, ref_params))
print('4D-OK')
"""
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "4D-OK" in proc.stdout


def test_pipeline_sp_validates():
    mesh = make_mesh({"data": 2, "pipe": 2, "sp": 2})
    with pytest.raises(ValueError, match="seq_axis owns attention"):
        PipelineParallel(CFG, optax.sgd(0.1), mesh, microbatches=2,
                         seq_axis="sp",
                         attention_fn=lambda q, k, v: q)
    with pytest.raises(ValueError, match="seq_attn must be"):
        PipelineParallel(CFG, optax.sgd(0.1), mesh, microbatches=2,
                         seq_axis="sp", seq_attn="bogus")
    pp = PipelineParallel(CFG, optax.sgd(0.1), mesh, microbatches=2,
                          seq_axis="sp")
    bad = np.zeros((4, 15), np.int32)  # S=15 not divisible by sp=2
    with pytest.raises(ValueError, match="not divisible by the sp=2"):
        pp.shard_batch(bad, bad)


def test_pipeline_validates(mesh_dp_pp):
    with pytest.raises(ValueError, match="divisible"):
        PipelineParallel(
            TransformerConfig(n_layers=3), optax.sgd(0.1), mesh_dp_pp, microbatches=2
        )
    mesh1 = make_mesh({"data": 8})
    with pytest.raises(ValueError, match="not in mesh"):
        PipelineParallel(CFG, optax.sgd(0.1), mesh1, microbatches=2)
