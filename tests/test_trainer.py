"""Trainer tests: the end-to-end single-device slice at toy scale —
loss decreases on learnable synthetic data, log-format parity, on-device
resize path, eval step."""

import re

import jax.numpy as jnp
import jax.random
import numpy as np
import optax

from tpu_sandbox.data import BatchLoader, synthetic_mnist
from tpu_sandbox.data.mnist import normalize
from tpu_sandbox.models import ConvNet
from tpu_sandbox.train import Trainer, TrainState, make_train_step
from tpu_sandbox.train.trainer import make_eval_step


def make_setup(image_size=None, lr=0.05, n=128):
    model = ConvNet()
    tx = optax.sgd(lr)
    shape = (1, *(image_size or (28, 28)), 1)
    state = TrainState.create(model, jax.random.key(0), jnp.zeros(shape), tx)
    step = make_train_step(model, tx, image_size=image_size)
    images, labels = synthetic_mnist(n=n, seed=0)
    loader = BatchLoader(normalize(images), labels.astype("int32"), 16, shuffle=True)
    return model, state, step, loader


def test_loss_decreases_on_synthetic():
    _, state, step, loader = make_setup()
    trainer = Trainer(step, log_every=1, verbose=False)
    state = trainer.fit(state, loader, epochs=6)
    first = np.mean(trainer.losses[:4])
    last = np.mean(trainer.losses[-4:])
    assert last < first * 0.8, (first, last)
    assert int(state.step) == 6 * len(loader)


def test_log_format_matches_reference(capsys):
    _, state, step, loader = make_setup(n=32)
    Trainer(step, log_every=1).fit(state, loader, epochs=1)
    out = capsys.readouterr().out
    # reference mnist_onegpu.py:76 format
    assert re.search(r"Epoch \[1/1\], Step \[1/2\], Loss: \d+\.\d{4}", out)
    assert "Training complete in: " in out


def test_ddp_log_format(capsys):
    _, state, step, loader = make_setup(n=32)
    Trainer(step, log_every=1, log_rank=0).fit(state, loader, epochs=1)
    out = capsys.readouterr().out
    # reference mnist_distributed.py:105 format
    assert re.search(r"Rank \[0\], Epoch \[1/1\], Step \[1/2\], Loss: \d+\.\d{4}", out)


def test_on_device_resize_path():
    # feed 28x28, train at 64x64: the resize lives inside the jit'd step
    _, state, step, loader = make_setup(image_size=(64, 64), n=32)
    images, labels = next(iter(loader))
    new_state, loss = step(state, images, labels)
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 1


def test_batch_stats_evolve_and_params_change():
    _, state, step, loader = make_setup(n=32)
    images, labels = next(iter(loader))
    # copy before stepping: the step donates its input state buffers
    old_kernel = np.asarray(state.params["conv1"]["kernel"]).copy()
    new_state, _ = step(state, jnp.asarray(images), jnp.asarray(labels))
    assert not np.allclose(np.asarray(new_state.params["conv1"]["kernel"]),
                           old_kernel)
    assert not np.allclose(np.asarray(new_state.batch_stats["bn1"]["mean"]), 0.0)


def test_eval_step_counts_correct():
    model, state, step, loader = make_setup()
    state = Trainer(step, verbose=False).fit(state, loader, epochs=6)
    eval_step = make_eval_step(model)
    images, labels = synthetic_mnist(n=64, seed=3)
    correct, loss = eval_step(state, normalize(images), labels.astype("int32"))
    assert float(correct) / 64 > 0.5  # learnable prototypes: well above chance
    assert np.isfinite(float(loss))


def test_grad_accumulation_matches_full_batch():
    """Without BN, k accumulated microbatches == one full-batch step exactly
    (mean CE is the mean of equal-size microbatch means; SGD is linear)."""
    model = ConvNet(use_bn=False)
    tx = optax.sgd(1e-2)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.standard_normal((8, 32, 32, 1), dtype=np.float32))
    labels = jnp.asarray(rng.integers(0, 10, size=8), jnp.int32)

    state0 = TrainState.create(model, jax.random.key(0), jnp.zeros((1, 32, 32, 1)), tx)
    full = make_train_step(model, tx, donate=False)
    acc = make_train_step(model, tx, accum_steps=4, donate=False)

    s_full, loss_full = full(state0, images, labels)
    s_acc, loss_acc = acc(state0, images, labels)
    np.testing.assert_allclose(float(loss_full), float(loss_acc), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
        ),
        s_full.params, s_acc.params,
    )


def test_grad_accumulation_with_bn_trains():
    """With BN the two are intentionally NOT identical (per-microbatch
    statistics, torch semantics); just check training progresses."""
    model = ConvNet()
    tx = optax.sgd(1e-2)
    images, labels = synthetic_mnist(n=16, seed=0)
    images = jnp.asarray(normalize(images))
    labels = jnp.asarray(labels.astype("int32"))
    state = TrainState.create(model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx)
    step = make_train_step(model, tx, accum_steps=2, donate=False)
    losses = []
    for _ in range(8):
        state, loss = step(state, images, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_periodic_checkpointing(tmp_path):
    from tpu_sandbox.train import checkpoint as ckpt

    model, state, step_fn, loader = make_setup(n=64)
    trainer = Trainer(step_fn, log_every=100, verbose=False,
                      ckpt_dir=str(tmp_path), ckpt_every=3)
    trainer.fit(state, loader, epochs=1)  # 64/16 = 4 steps -> save at 3
    assert ckpt.latest_step(tmp_path) == 3
    restored = ckpt.restore(tmp_path, state)
    assert int(restored.step) == 3


def test_remat_step_matches_plain_step():
    """make_train_step(remat=True) — the capacity lever — must be a pure
    memory/compute trade: identical loss, updated params, and BN stats to
    the plain step from the same state."""
    import jax

    model = ConvNet()
    tx = optax.sgd(1e-2)
    images, labels = synthetic_mnist(n=8, seed=3)
    images, labels = normalize(images), labels.astype("int32")
    state0 = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 32, 32, 1)), tx
    )

    def run(remat):
        step = make_train_step(model, tx, image_size=(32, 32),
                               donate=False, remat=remat)
        return step(state0, jnp.asarray(images), jnp.asarray(labels))

    (sp, lp), (sr, lr) = run(False), run(True)
    np.testing.assert_allclose(float(lr), float(lp), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        (sr.params, sr.batch_stats), (sp.params, sp.batch_stats),
    )
