"""All-reduce-sum toy across N ranks — TPU-native rebuild of the reference
``allreduce_toy.py`` (same flags, same output lines).

Reference behavior (allreduce_toy.py:20-48): N processes each draw a random
int in [0, 10), all-reduce-sum it over NCCL, barrier, and ranks 0 and 1 print
``rank: R, step: S, value: V, reduced sum: T.`` for 10 steps (the ``--steps``
flag existed but was ignored — setup() hardcoded 10 at :48; here the flag
works, defaulting to 10 so the default launch matches the reference output).

TPU-native shape: ranks are devices of ONE process (no mp.spawn), the group
is built once (the reference created a fresh ``dist.new_group`` every step,
:26-27 — a communicator leak XLA has no analogue of), the all-reduce is a
jit'd ``lax.psum`` over the mesh axis, and the barrier is a psum'd unit
token. ``--backend`` / ``--init-method`` / ``--rank`` are accepted for
launch-compatibility; backend and rendezvous are JAX's concern now.
"""

import argparse

import numpy as np


def run(group, world_size: int, steps: int) -> None:
    for step in range(1, steps + 1):
        # per-rank host RNG, unseeded — parity with torch.randint at :23
        values = np.random.randint(0, 10, size=(world_size,)).astype(np.int32)
        reduced = np.asarray(group.all_reduce(values, "sum"))
        group.barrier()
        for rank in range(min(2, world_size)):
            print(
                "rank: {}, step: {}, value: {}, reduced sum: {}.".format(
                    rank, step, values[rank], reduced[rank]
                )
            )


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--backend", type=str, default="xla",
                        help="Accepted for reference parity; XLA picks the fabric.")
    parser.add_argument("-i", "--init-method", type=str,
                        default="tcp://127.0.0.1:23456",
                        help="Accepted for reference parity; rendezvous is jax.distributed.")
    parser.add_argument("-s", "--world_size", type=int, default=None,
                        help="Number of ranks participating in the job.")
    parser.add_argument("-r", "--rank", type=int, default=None,
                        help="Accepted for reference parity; ranks are devices here.")
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--force-cpu", action="store_true",
                        help="Use virtual CPU devices even if an accelerator is present.")
    args = parser.parse_args()

    from tpu_sandbox.utils.cli import ensure_devices

    world_size = args.world_size or 1
    devices = ensure_devices(world_size, force_cpu=args.force_cpu)

    from tpu_sandbox.parallel.collectives import CollectiveGroup
    from tpu_sandbox.runtime import bootstrap
    from tpu_sandbox.runtime.mesh import make_mesh

    bootstrap.init()
    mesh = make_mesh({"data": world_size}, devices=devices)
    group = CollectiveGroup(mesh, "data")
    for rank in range(world_size):
        print(f"--> done setting up rank={rank}")

    run(group, world_size, args.steps)
    bootstrap.cleanup()


if __name__ == "__main__":
    main()
