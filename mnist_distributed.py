"""Data-parallel big-image MNIST training — TPU-native rebuild of the
reference ``mnist_distributed.py`` (same flags, same log lines, same
OOM-workaround experiment: bs=5 per rank, effective batch 5*world_size).

Reference behavior (mnist_distributed.py:48-127): spawn one process per GPU,
global rank = nr*gpus + gpu, NCCL process group, DDP-wrapped ConvNet,
DistributedSampler sharding (never reshuffled — no set_epoch call), CE +
SGD(1e-4), rank-0 prints ``Rank [r], Epoch [e/E], Step [s/S], Loss: L``
every 100 steps, wall-clock total. Its multi-node flags never actually
worked (hardcoded localhost master + fresh random port per invocation).

TPU-native shape: no spawning — ranks are devices of one process
(``-g`` = number of local devices; CPU-virtualized when the chip count is
smaller). The DDP engine is ``tpu_sandbox.parallel.DataParallel``: one jit'd
shard_map step with pmean'd grads, replicated params, per-replica BN stats.
Real multi-host runs initialize via tpu_sandbox.runtime.bootstrap
(jax.distributed) instead of the reference's broken localhost rendezvous.
"""

import argparse

from tpu_sandbox.utils.cli import (
    add_checkpoint_cli,
    add_elastic_cli,
    add_grad_compress_cli,
    add_overlap_cli,
)

IMAGE_SHAPE = [3000, 3000]


def load_training_arrays(args, world_size):
    """Real MNIST if available, synthetic otherwise; normalized and trimmed
    to --limit-steps (shared by the single- and multi-process paths)."""
    from tpu_sandbox.data import load_mnist, synthetic_mnist
    from tpu_sandbox.data.mnist import normalize

    try:
        images, labels = load_mnist("train", args.data_dir)
    except FileNotFoundError:
        print("MNIST IDX files not found; using deterministic synthetic MNIST")
        images, labels = synthetic_mnist(n=args.synthetic_n, seed=0)
    images = normalize(images)
    labels = labels.astype("int32")
    if args.limit_steps:
        keep = args.limit_steps * args.batch_size * world_size
        images, labels = images[:keep], labels[:keep]
    return images, labels


def _zero_sgd_note():
    print("note: --zero with plain SGD shards no optimizer state "
          "(SGD is stateless); use --opt momentum|adamw for the memory win")


def make_optimizer(args):
    """--opt picks the optimizer; the reference schedule is plain SGD(1e-4)
    (mnist_distributed.py:65 in the reference), kept as the default for log
    parity. --zero only has state to shard for the stateful choices."""
    import optax

    if args.opt == "sgd":
        if args.zero and not getattr(args, "worker", False):
            _zero_sgd_note()
        return optax.sgd(learning_rate=1e-4)
    if args.opt == "momentum":
        return optax.sgd(learning_rate=1e-4, momentum=0.9)
    return optax.adamw(learning_rate=1e-4)


def train(args, world_size):
    import jax
    import jax.numpy as jnp

    from tpu_sandbox.data import ShardedBatchLoader
    from tpu_sandbox.models import pick_convnet
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.runtime import bootstrap
    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.train import Trainer, TrainState
    from tpu_sandbox.utils.cli import ensure_devices

    devices = ensure_devices(world_size, force_cpu=args.force_cpu)
    bootstrap.init()
    mesh = make_mesh({"data": world_size}, devices=devices)

    rng = jax.random.key(0)  # parity: torch.manual_seed(0), reference :51
    image_shape = [args.image_size, args.image_size]
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    model = pick_convnet(args.image_size, plan=args.plan,
                         num_classes=10, dtype=dtype)
    tx = make_optimizer(args)

    images, labels = load_training_arrays(args, world_size)

    # bs per rank (reference :60-61); sampler shards, loader never reshuffles
    # across epochs (reference quirk: no sampler.set_epoch, SURVEY §2.1 C14)
    loader = ShardedBatchLoader(
        images, labels, args.batch_size, world_size, shuffle=True, seed=0
    )

    state = TrainState.create(model, rng, jnp.zeros([1, *image_shape, 1], dtype), tx)
    if args.ckpt_dir and args.resume:
        from tpu_sandbox.train import checkpoint as ckpt

        if ckpt.latest_step(args.ckpt_dir) is not None:
            state = ckpt.restore(args.ckpt_dir, state)
            print(f"resumed from step {int(state.step)}")
    dp = DataParallel(model, tx, mesh, image_size=tuple(image_shape),
                      zero=args.zero, grad_compress=args.grad_compress,
                      error_feedback=not args.no_error_feedback,
                      overlap_grad_sync=args.overlap_grad_sync,
                      bucket_mb=args.bucket_mb)
    dstate = dp.shard_state(state)

    def step(s, images_np, labels_np):
        return dp.train_step(s, *dp.shard_batch(images_np, labels_np))

    trainer = Trainer(step, log_every=args.log_every, log_rank=0,
                      ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
                      state_for_checkpoint=dp.unshard_state)
    dstate = trainer.fit(dstate, loader, args.epochs, set_epoch=False,
                         prefetch=args.prefetch)
    if args.ckpt_dir:
        from tpu_sandbox.train import checkpoint as ckpt

        # checkpoint the single-device view (rank 0's BN stats), the same
        # layout mnist_onegpu saves — the two scripts' checkpoints interop
        print(f"saved checkpoint at step "
              f"{ckpt.save(args.ckpt_dir, dp.unshard_state(dstate))}")
    bootstrap.cleanup()


def train_multiprocess_worker(args, world_size):
    """One OS process = one rank with one CPU device — the reference's
    actual topology (one proc per GPU, mnist_distributed.py:127), over
    jax.distributed + Gloo instead of NCCL. Each process feeds its
    DistributedSampler shard and assembles the global batch with
    make_array_from_process_local_data; the jit'd shard_map step then runs
    SPMD across processes with cross-process grad pmean."""
    from tpu_sandbox.utils.cli import configure_worker_cpu

    configure_worker_cpu(1)

    import jax  # noqa: F401  (platform configured above, before first use)
    import numpy as np

    from tpu_sandbox.runtime import Heartbeat, bootstrap, wait_for_world
    from tpu_sandbox.runtime.kvstore import KVClient

    # health plane: beat into the parent's KV store for the whole run and
    # rendezvous with a deadline BEFORE touching jax.distributed, so a rank
    # that never starts fails fast with names instead of hanging the group
    # (the reference's failure mode — SURVEY §5)
    hb = None
    if args.kv_port:
        kv = KVClient(port=int(args.kv_port))
        hb = Heartbeat(kv, args.rank, interval=1.0).start()
        wait_for_world(kv, world_size, args.rank, timeout=120.0)

    bootstrap.init(
        coordinator=f"127.0.0.1:{args.port}",
        num_processes=world_size,
        process_id=args.rank,
    )

    import jax.numpy as jnp

    from tpu_sandbox.data import BatchLoader
    from tpu_sandbox.data.sampler import DistributedSampler
    from tpu_sandbox.models import pick_convnet
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.runtime.multihost import global_batch_from_local
    from tpu_sandbox.train import Trainer, TrainState

    rank = args.rank
    mesh = make_mesh({"data": world_size})  # one device per process
    image_shape = [args.image_size, args.image_size]
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32

    # same seed everywhere -> same init; shard_state places it replicated
    model = pick_convnet(args.image_size, plan=args.plan,
                         num_classes=10, dtype=dtype)
    tx = make_optimizer(args)
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros([1, *image_shape, 1], dtype), tx
    )

    images, labels = load_training_arrays(args, world_size)
    sampler = DistributedSampler(len(images), world_size, rank, seed=0)
    local_loader = BatchLoader(images, labels, args.batch_size,
                               sampler=sampler, drop_last=True)

    class GlobalLoader:
        """Each process contributes its sampler shard; batches come out as
        global process-spanning arrays (make_array_from_process_local_data)."""

        def __len__(self):
            return len(local_loader)

        def set_epoch(self, epoch):
            local_loader.set_epoch(epoch)

        def __iter__(self):
            for imgs, labs in local_loader:
                yield (
                    global_batch_from_local(mesh, np.asarray(imgs)),
                    global_batch_from_local(mesh, np.asarray(labs)),
                )

    dp = DataParallel(model, tx, mesh, image_size=tuple(image_shape),
                      zero=args.zero, grad_compress=args.grad_compress,
                      error_feedback=not args.no_error_feedback,
                      overlap_grad_sync=args.overlap_grad_sync,
                      bucket_mb=args.bucket_mb)
    dstate = dp.shard_state(state)
    trainer = Trainer(dp.train_step, log_every=args.log_every, log_rank=0,
                      verbose=rank == 0)
    trainer.fit(dstate, GlobalLoader(), args.epochs, set_epoch=False,
                prefetch=args.prefetch)
    bootstrap.cleanup()
    if hb is not None:
        hb.stop(deregister=True)


def train_elastic_worker(args, world_size):
    """One rank of an elastic generation: heartbeat + generation-scoped
    rendezvous, fault injection from the env plan, resumable training with
    coordination-free checkpointing (host: rank 0 writes npz files; sharded:
    every rank writes its own shard and rank 0 seals a manifest via
    two-phase commit — required under --zero, whose optimizer shards live on
    every rank), and SIGTERM → save → exit 75 so the supervisor restarts
    the generation without charging its budget."""
    import os
    import sys

    from tpu_sandbox.utils.cli import configure_worker_cpu

    configure_worker_cpu(1)

    import jax
    import numpy as np

    from tpu_sandbox.runtime import Heartbeat, bootstrap, wait_for_world
    from tpu_sandbox.runtime.faults import FaultInjector, FaultPlan
    from tpu_sandbox.runtime.kvstore import KVClient, for_job
    from tpu_sandbox.train import (
        PREEMPTED_EXIT_CODE,
        ElasticEnv,
        Preempted,
        PreemptionHandler,
        TrainState,
        build_elastic_checkpoint,
        train_resumable,
    )

    rank = args.rank
    eenv = ElasticEnv.from_env()  # generation + owning host agent (if any)
    # job-scoped store view: under the cluster scheduler every runtime key
    # this rank touches (heartbeats, fault claims, barriers, job/done)
    # lives inside job/<id>/ — a neighbor job can never see or be seen
    kv = for_job(KVClient(port=int(args.kv_port)), eenv.job_id)
    hb = Heartbeat(kv, rank, interval=0.5).start()
    preemption = PreemptionHandler(kv)
    plan = FaultPlan.from_env()
    injector = None
    if plan.faults:
        # hang_heartbeat: stop beating but stay alive — exercises the
        # supervisor's watchdog (wedged-not-dead) path; agent_id routes
        # kill_agent/partition_host to this rank's host agent's mailbox
        injector = FaultInjector(
            plan, rank, kv,
            on_hang_heartbeat=lambda: hb.stop(deregister=False),
            agent_id=eenv.agent_id,
        )
    wait_for_world(kv, world_size, rank, timeout=120.0)
    bootstrap.init(
        coordinator=f"127.0.0.1:{args.port}",
        num_processes=world_size,
        process_id=rank,
    )
    # AFTER bootstrap.init: jax.distributed installs XLA's own SIGTERM
    # notifier, and whoever installs last owns the signal — ours must win
    # or a preemption notice trains straight through to completion
    preemption.install()

    import jax.numpy as jnp

    from tpu_sandbox.data import BatchLoader
    from tpu_sandbox.data.sampler import DistributedSampler
    from tpu_sandbox.models import pick_convnet
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.runtime.multihost import global_batch_from_local

    mesh = make_mesh({"data": world_size})
    image_shape = [args.image_size, args.image_size]
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    model = pick_convnet(args.image_size, plan=args.plan,
                         num_classes=10, dtype=dtype)
    tx = make_optimizer(args)
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros([1, *image_shape, 1], dtype), tx
    )
    template = state.host_view()  # restore target, before sharding

    images, labels = load_training_arrays(args, world_size)
    sampler = DistributedSampler(len(images), world_size, rank, seed=0)
    local_loader = BatchLoader(images, labels, args.batch_size,
                               sampler=sampler, drop_last=True)

    class GlobalLoader:
        def __len__(self):
            return len(local_loader)

        def set_epoch(self, epoch):
            local_loader.set_epoch(epoch)

        def __iter__(self):
            for imgs, labs in local_loader:
                yield (
                    global_batch_from_local(mesh, np.asarray(imgs)),
                    global_batch_from_local(mesh, np.asarray(labs)),
                )

    # donate=False: the non-finite guard keeps the PREVIOUS state when an
    # update is discarded, which donated (invalidated) buffers cannot do
    dp = DataParallel(model, tx, mesh, image_size=tuple(image_shape),
                      zero=args.zero, donate=False,
                      grad_compress=args.grad_compress,
                      error_feedback=not args.no_error_feedback,
                      overlap_grad_sync=args.overlap_grad_sync,
                      bucket_mb=args.bucket_mb)

    # per-boundary preemption vote: OR this rank's flag across the world
    # through a real collective, so every rank reaches the same stop
    # verdict at the same step (see train_resumable's docstring)
    _vote_sum = jax.jit(jnp.sum)

    def agree_preempt(flag: bool) -> bool:
        local = np.asarray([1.0 if flag else 0.0], np.float32)
        return bool(int(_vote_sum(global_batch_from_local(mesh, local))) > 0)

    gen = eenv.generation
    restore_fn = None
    save_fn = None
    verifier = None
    if args.ckpt_dir:
        save_fn, restore_fn, verifier = build_elastic_checkpoint(
            args.ckpt_dir, dp=dp, template=template, rank=rank,
            world_size=world_size,
            sharded=bool(args.ckpt_sharded or args.zero),
            kv=kv, injector=injector,
            verify_interval=args.ckpt_verify_interval,
            commit_timeout=float(
                os.environ.get("TPU_SANDBOX_COMMIT_TIMEOUT", 60.0)
            ),
            generation=gen, verbose=rank == 0,
            compress=args.ckpt_compress,
        )
    if verifier is not None:
        verifier.start()
    dstate = dp.shard_state(state)
    try:
        dstate, report = train_resumable(
            dp.train_step, dstate, GlobalLoader(), args.epochs,
            save_fn=save_fn, restore_fn=restore_fn,
            ckpt_every=args.ckpt_every, preemption=preemption,
            agree_fn=agree_preempt if world_size > 1 else None,
            injector=injector, log_every=args.log_every, log_rank=rank,
            verbose=rank == 0, set_epoch=False, prefetch=args.prefetch,
        )
        if rank == 0:
            resumed = (f"resumed from step {report.resumed_step}"
                       if report.resumed_step is not None else "fresh start")
            print(f"[gen {gen}] {resumed}; applied {report.steps_applied} "
                  f"step(s), final step {report.final_step}")
        if save_fn is not None:
            save_fn(dstate, report.final_step, args.epochs, 0)
    except Preempted:
        hb.stop(deregister=True)
        bootstrap.cleanup()
        sys.exit(PREEMPTED_EXIT_CODE)
    except BaseException:
        # a peer's preemption can surface here as a collective/dispatch
        # error on this rank; if the preempt flag is up, classify this exit
        # as preempted too so the supervisor's initiator-only rule holds
        if preemption.requested():
            hb.stop(deregister=True)
            sys.exit(PREEMPTED_EXIT_CODE)
        raise
    finally:
        preemption.uninstall()
        if verifier is not None:
            verifier.stop()
    bootstrap.cleanup()
    hb.stop(deregister=True)


def _elastic_passthrough(args):
    """The worker-facing flag subset, re-serialized for child processes
    (shared by the single-host supervisor path and the agent topology —
    their workers must parse identically)."""
    passthrough = [
        "-n", str(args.nodes), "-g", str(args.gpus),
        "--epochs", str(args.epochs), "--batch-size", str(args.batch_size),
        "--image-size", str(args.image_size),
        "--synthetic-n", str(args.synthetic_n),
        "--log-every", str(args.log_every), "--dtype", args.dtype,
        "--plan", args.plan, "--opt", args.opt,
    ]
    if args.data_dir:
        passthrough += ["--data-dir", args.data_dir]
    if args.limit_steps:
        passthrough += ["--limit-steps", str(args.limit_steps)]
    if args.ckpt_dir:
        passthrough += ["--ckpt-dir", args.ckpt_dir]
    if args.ckpt_every:
        passthrough += ["--ckpt-every", str(args.ckpt_every)]
    if args.zero:
        # safe under --elastic since PR 3: ZeRO auto-selects the sharded
        # checkpoint backend, so every rank's optimizer shard is persisted
        passthrough += ["--zero"]
    if args.ckpt_sharded:
        passthrough += ["--ckpt-sharded"]
    if args.ckpt_verify_interval:
        passthrough += ["--ckpt-verify-interval",
                        str(args.ckpt_verify_interval)]
    if args.ckpt_compress:
        passthrough += ["--ckpt-compress"]
    if args.grad_compress != "none":
        passthrough += ["--grad-compress", args.grad_compress]
    if args.no_error_feedback:
        passthrough += ["--no-error-feedback"]
    if args.overlap_grad_sync:
        passthrough += ["--overlap-grad-sync"]
    if args.bucket_mb != 25.0:
        passthrough += ["--bucket-mb", str(args.bucket_mb)]
    if args.prefetch:
        passthrough += ["--prefetch"]
    return passthrough


def _validate_fault_plan():
    from tpu_sandbox.runtime.faults import FaultPlan

    try:
        # fail fast here: a malformed plan would otherwise crash every
        # worker at startup and silently burn the whole restart budget
        FaultPlan.from_env()
    except (TypeError, ValueError) as e:
        raise SystemExit(f"invalid TPU_SANDBOX_FAULT_PLAN: {e}") from e


def spawn_elastic(args, world_size):
    """Run the multiprocess topology under the elastic supervisor: crashes
    and preemptions tear the generation down and relaunch it; workers
    resume from the newest valid checkpoint with exact data order."""
    import os
    import sys

    from tpu_sandbox.runtime.bootstrap import find_free_port
    from tpu_sandbox.runtime.supervisor import (
        RestartBudgetExceeded,
        Supervisor,
    )

    _validate_fault_plan()
    if not args.ckpt_dir:
        print("note: --elastic without --ckpt-dir restarts from step 0 "
              "(pass --ckpt-dir/--ckpt-every to resume where the crash hit)")

    passthrough = _elastic_passthrough(args)

    def build(gen, kv_port):
        port = find_free_port()  # fresh coordinator port per generation
        base = [sys.executable, __file__, "--elastic-worker",
                "--port", port, "--kv-port", str(kv_port)] + passthrough
        return [base + ["--rank", str(r)] for r in range(world_size)]

    sup = Supervisor(
        world_size, build,
        max_restarts=args.max_restarts,
        backoff=float(os.environ.get("TPU_SANDBOX_BACKOFF", 1.0)),
        heartbeat_timeout=float(
            os.environ.get("TPU_SANDBOX_WATCHDOG_TIMEOUT", 60.0)
        ),
        grace=float(os.environ.get("TPU_SANDBOX_WATCHDOG_GRACE", 180.0)),
        term_timeout=float(
            # how long a SIGTERM'd survivor (usually wedged in a collective
            # whose peer died) gets before the SIGKILL escalation
            os.environ.get("TPU_SANDBOX_TERM_TIMEOUT", 30.0)
        ),
    )
    try:
        result = sup.run()
    except RestartBudgetExceeded as e:
        raise SystemExit(str(e))
    if not result.ok:
        # preempted from outside: saved state, clean stop, propagate 75
        sys.exit(result.generations[-1].exit_codes[0] or 0)


def _agent_config_from_env(args, world_size, kv_port):
    """AgentConfig from CLI + the same env knobs the supervisor honors,
    plus the agent-plane extras (agent heartbeat timeout, lease TTL)."""
    import os

    from tpu_sandbox.runtime.host_agent import AgentConfig

    def knob(name, default):
        return float(os.environ.get(name, default))

    return AgentConfig(
        agent_id=args.agent_id or 0,
        num_agents=args.agents,
        world_size=world_size,
        kv_port=kv_port,
        job_id=args.job_id or os.environ.get("TPU_SANDBOX_JOB_ID", ""),
        max_restarts=args.max_restarts,
        backoff=knob("TPU_SANDBOX_BACKOFF", 1.0),
        heartbeat_timeout=knob("TPU_SANDBOX_WATCHDOG_TIMEOUT", 60.0),
        grace=knob("TPU_SANDBOX_WATCHDOG_GRACE", 180.0),
        term_timeout=knob("TPU_SANDBOX_TERM_TIMEOUT", 30.0),
        agent_timeout=knob("TPU_SANDBOX_AGENT_TIMEOUT", 10.0),
        lease_ttl=knob("TPU_SANDBOX_LEASE_TTL", 3.0),
        ack_timeout=knob("TPU_SANDBOX_ACK_TIMEOUT", 60.0),
        agent_wait=knob("TPU_SANDBOX_AGENT_WAIT", 120.0),
    )


def run_host_agent(args, world_size):
    """Run ONE host agent of an --agents N job (the per-process entry the
    AgentLauncher spawns; also usable directly, one invocation per host,
    with --leader hosting the KV store on the first host)."""
    import sys

    from tpu_sandbox.runtime.host_agent import HostAgent
    from tpu_sandbox.runtime.kvstore import KVServer

    if args.agents < 1:
        raise SystemExit("--agent-id requires --agents N (the topology)")
    if not (0 <= args.agent_id < args.agents):
        raise SystemExit(
            f"--agent-id {args.agent_id} out of range for "
            f"--agents {args.agents}"
        )
    server = None
    if args.leader:
        # bind/token make the store reachable off-host: --kv-bind 0.0.0.0
        # + TPU_SANDBOX_KV_TOKEN in the env (KVServer/KVClient both read
        # it, so workers inherit the secret without a flag)
        server = KVServer(port=int(args.kv_port or 0), bind=args.kv_bind)
        print(f"[agent {args.agent_id}] hosting KV store on "
              f"{args.kv_bind}:{server.port}", flush=True)
        kv_port = server.port
    elif args.kv_port:
        kv_port = int(args.kv_port)
    else:
        raise SystemExit("--agent-id needs --kv-port (or --leader)")

    passthrough = _elastic_passthrough(args)

    def rank_cmd(gen, rank, coord_port):
        return [sys.executable, __file__, "--elastic-worker",
                "--port", str(coord_port), "--kv-port", str(kv_port),
                *passthrough, "--rank", str(rank)]

    cfg = _agent_config_from_env(args, world_size, kv_port)
    try:
        rc = HostAgent(cfg, rank_cmd).run()
    finally:
        if server is not None:
            server.stop()
    sys.exit(rc)


def spawn_elastic_agents(args, world_size):
    """Cross-host elastic topology, proven on one machine: an
    AgentLauncher (the cluster-scheduler stand-in) owns the KV store and
    spawns --agents N HostAgent processes; the agents elect a leader that
    drives generation lifecycle, and the launcher replaces any agent that
    dies (host replacement). See runtime/host_agent.py."""
    import sys

    from tpu_sandbox.runtime.host_agent import AgentLauncher

    _validate_fault_plan()
    if world_size < args.agents:
        raise SystemExit(
            f"world size {world_size} gives --agents {args.agents} "
            "nothing to run on some hosts (every agent owns >= 1 rank)"
        )
    if not args.ckpt_dir:
        print("note: --elastic without --ckpt-dir restarts from step 0 "
              "(pass --ckpt-dir/--ckpt-every to resume where the crash hit)")

    passthrough = _elastic_passthrough(args)

    def agent_cmd(aid, kv_port):
        return [sys.executable, __file__, "--elastic",
                "--agents", str(args.agents), "--agent-id", str(aid),
                "--kv-port", str(kv_port),
                "--max-restarts", str(args.max_restarts), *passthrough]

    rc = AgentLauncher(args.agents, agent_cmd).run()
    if rc:
        sys.exit(rc)


def run_cluster_pool(args, world_size):
    """Multi-tenant cluster mode: gang-schedule this training job through
    the durable queue of runtime/scheduler.py on a pool of --pool host
    slots. Same agent topology as --agents N, but admitted (and possibly
    queued or preempted) by the scheduler instead of launched directly —
    the entry point that exercises one mesh as one tenant of a shared
    pool."""
    import sys

    from tpu_sandbox.runtime.scheduler import ClusterScheduler, JobSpec

    _validate_fault_plan()
    agents = args.agents or 1
    if not args.ckpt_dir:
        print("note: --elastic without --ckpt-dir restarts from step 0 "
              "(pass --ckpt-dir/--ckpt-every to resume where the crash hit)")

    passthrough = _elastic_passthrough(args)
    job_id = args.job_id or "job0"
    spec = JobSpec(
        job_id=job_id,
        hosts=agents,
        world_size=world_size,
        agent_argv=[sys.executable, __file__, "--elastic",
                    "--agents", str(agents), "--agent-id", "{agent_id}",
                    "--kv-port", "{kv_port}", "--job-id", "{job_id}",
                    "--max-restarts", str(args.max_restarts), *passthrough],
        priority=args.priority,
    )
    with ClusterScheduler(args.pool) as sched:
        sched.submit(spec)
        states = sched.serve()
    state = states.get(job_id)
    print(f"[cluster] job {job_id!r} finished: {state}", flush=True)
    if state != "done":
        sys.exit(1)


def spawn_multiprocess(args, world_size):
    import subprocess
    import sys
    import time

    from tpu_sandbox.runtime.bootstrap import find_free_port

    if args.zero and args.opt == "sgd":
        _zero_sgd_note()  # workers suppress it; say it once from here

    if args.ckpt_dir or args.resume:
        # orbax multi-controller checkpointing needs coordinated commits;
        # refuse loudly rather than silently not saving
        raise SystemExit(
            "--ckpt-dir/--resume are not supported with --multiprocess yet; "
            "run the single-process engine (-g N) for checkpointed training"
        )
    from tpu_sandbox.runtime import Watchdog
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    kv_server = KVServer()
    port = find_free_port()
    cmd_base = [sys.executable, __file__, "--worker", "--port", port,
                "--kv-port", str(kv_server.port)]
    passthrough = [
        "-n", str(args.nodes), "-g", str(args.gpus),
        "--epochs", str(args.epochs), "--batch-size", str(args.batch_size),
        "--image-size", str(args.image_size),
        "--synthetic-n", str(args.synthetic_n),
        "--log-every", str(args.log_every), "--dtype", args.dtype,
        "--plan", args.plan, "--opt", args.opt,
    ]
    if args.data_dir:
        passthrough += ["--data-dir", args.data_dir]
    if args.limit_steps:
        passthrough += ["--limit-steps", str(args.limit_steps)]
    if args.zero:
        passthrough += ["--zero"]
    if args.grad_compress != "none":
        passthrough += ["--grad-compress", args.grad_compress]
    if args.no_error_feedback:
        passthrough += ["--no-error-feedback"]
    if args.overlap_grad_sync:
        passthrough += ["--overlap-grad-sync"]
    if args.bucket_mb != 25.0:
        passthrough += ["--bucket-mb", str(args.bucket_mb)]
    if args.prefetch:
        passthrough += ["--prefetch"]
    procs = [
        subprocess.Popen(cmd_base + ["--rank", str(r)] + passthrough)
        for r in range(world_size)
    ]
    # health plane: workers heartbeat into our KV store; the watchdog
    # catches the wedged-not-dead case (a rank alive as a process but
    # silent for >60s — e.g. stuck in a collective whose peer vanished)
    # that exit-code polling alone can never see
    import os

    watchdog = Watchdog(
        KVClient(port=kv_server.port), world_size,
        timeout=float(os.environ.get("TPU_SANDBOX_WATCHDOG_TIMEOUT", 60.0)),
        grace=float(os.environ.get("TPU_SANDBOX_WATCHDOG_GRACE", 180.0)),
    )

    def _kill_all(reason: str):
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()  # survivor ignored SIGTERM (wedged collective)
                p.wait()
        kv_server.stop()
        raise SystemExit(
            f"{reason}; worker exit codes: {[p.poll() for p in procs]}"
        )

    # fail fast: a dead worker leaves its peers blocked in a collective, so
    # on the first nonzero exit kill the survivors (the reference's mp.spawn
    # does the same)
    codes = [None] * world_size
    while any(c is None for c in codes):
        for i, p in enumerate(procs):
            if codes[i] is None:
                codes[i] = p.poll()
        if any(c not in (None, 0) for c in codes):
            _kill_all("worker failure detected")
        # only ranks whose PROCESS is still running count: a cleanly-exited
        # rank deregisters its heartbeat and must not read as dead
        dead = [r for r in watchdog.dead_ranks() if codes[r] is None]
        if dead:
            _kill_all(f"watchdog: rank(s) {dead} stopped heartbeating")
        time.sleep(0.2)
    # loop exit <=> every worker finished with code 0
    kv_server.stop()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", "--nodes", type=int, default=1, metavar="N",
                        help="number of hosts (parity flag; >1 uses jax.distributed)")
    parser.add_argument("-g", "--gpus", type=int, default=1,
                        help="number of devices (ranks) per node")
    parser.add_argument("-nr", "--nr", type=int, default=0,
                        help="ranking of this node (parity flag)")
    parser.add_argument("--epochs", type=int, default=2, metavar="N",
                        help="number of epochs")
    parser.add_argument("--batch-size", type=int, default=5,
                        help="per-rank batch size (reference :60-61)")
    parser.add_argument("--image-size", type=int, default=IMAGE_SHAPE[0])
    parser.add_argument("--data-dir", type=str, default=None)
    parser.add_argument("--synthetic-n", type=int, default=60000)
    parser.add_argument("--limit-steps", type=int, default=None)
    parser.add_argument("--log-every", type=int, default=100)
    parser.add_argument("--opt", choices=["sgd", "momentum", "adamw"],
                        default="sgd",
                        help="optimizer (default: the reference's plain "
                             "SGD 1e-4; momentum/adamw give --zero real "
                             "state to shard)")
    parser.add_argument("--zero", action="store_true",
                        help="ZeRO-1: shard optimizer state over the data "
                             "axis (same math, 1/N the optimizer memory)")
    parser.add_argument("--plan",
                        choices=["auto", "s2dt", "s2d", "plain"],
                        default="auto",
                        help="ConvNet execution plan: s2dt = transposed "
                             "space-to-depth (models/convnet_s2d_t.py), "
                             "s2d = NHWC space-to-depth "
                             "(models/convnet_s2d.py) - same function as "
                             "the plain net either way, tested; auto "
                             "picks s2dt on TPU when the image "
                             "size allows")
    parser.add_argument("--dtype", choices=["bf16", "fp32"], default="bf16")
    add_checkpoint_cli(parser)
    add_grad_compress_cli(parser)
    add_overlap_cli(parser)
    parser.add_argument("--force-cpu", action="store_true",
                        help="use virtual CPU devices even if an accelerator is present")
    parser.add_argument("--multiprocess", action="store_true",
                        help="one OS process per rank over jax.distributed + "
                             "Gloo (the reference's actual topology)")
    add_elastic_cli(parser)
    parser.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--elastic-worker", action="store_true",
                        help=argparse.SUPPRESS)
    parser.add_argument("--rank", type=int, default=0, help=argparse.SUPPRESS)
    parser.add_argument("--port", type=str, default="", help=argparse.SUPPRESS)
    parser.add_argument("--kv-port", type=str, default="",
                        help=argparse.SUPPRESS)
    args = parser.parse_args()
    world_size = args.gpus * args.nodes  # reference :123
    if args.worker:
        train_multiprocess_worker(args, world_size)
    elif args.elastic_worker:
        train_elastic_worker(args, world_size)
    elif args.agent_id is not None:
        run_host_agent(args, world_size)
    elif args.elastic and args.pool:
        run_cluster_pool(args, world_size)
    elif args.elastic and args.agents:
        spawn_elastic_agents(args, world_size)
    elif args.elastic:
        spawn_elastic(args, world_size)
    elif args.multiprocess:
        spawn_multiprocess(args, world_size)
    else:
        train(args, world_size)


if __name__ == "__main__":
    main()
