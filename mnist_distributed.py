"""Data-parallel big-image MNIST training — TPU-native rebuild of the
reference ``mnist_distributed.py`` (same flags, same log lines, same
OOM-workaround experiment: bs=5 per rank, effective batch 5*world_size).

Reference behavior (mnist_distributed.py:48-127): spawn one process per GPU,
global rank = nr*gpus + gpu, NCCL process group, DDP-wrapped ConvNet,
DistributedSampler sharding (never reshuffled — no set_epoch call), CE +
SGD(1e-4), rank-0 prints ``Rank [r], Epoch [e/E], Step [s/S], Loss: L``
every 100 steps, wall-clock total. Its multi-node flags never actually
worked (hardcoded localhost master + fresh random port per invocation).

TPU-native shape: no spawning — ranks are devices of one process
(``-g`` = number of local devices; CPU-virtualized when the chip count is
smaller). The DDP engine is ``tpu_sandbox.parallel.DataParallel``: one jit'd
shard_map step with pmean'd grads, replicated params, per-replica BN stats.
Real multi-host runs initialize via tpu_sandbox.runtime.bootstrap
(jax.distributed) instead of the reference's broken localhost rendezvous.
"""

import argparse

IMAGE_SHAPE = [3000, 3000]


def train(args, world_size):
    import jax
    import jax.numpy as jnp
    import optax

    from tpu_sandbox.data import ShardedBatchLoader, load_mnist, synthetic_mnist
    from tpu_sandbox.data.mnist import normalize
    from tpu_sandbox.models import ConvNet
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.runtime import bootstrap
    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.train import Trainer, TrainState
    from tpu_sandbox.utils.cli import ensure_devices

    devices = ensure_devices(world_size, force_cpu=args.force_cpu)
    bootstrap.init()
    mesh = make_mesh({"data": world_size}, devices=devices)

    rng = jax.random.key(0)  # parity: torch.manual_seed(0), reference :51
    image_shape = [args.image_size, args.image_size]
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    model = ConvNet(num_classes=10, dtype=dtype)
    tx = optax.sgd(learning_rate=1e-4)  # reference :65

    try:
        images, labels = load_mnist("train", args.data_dir)
    except FileNotFoundError:
        print("MNIST IDX files not found; using deterministic synthetic MNIST")
        images, labels = synthetic_mnist(n=args.synthetic_n, seed=0)
    images = normalize(images)
    labels = labels.astype("int32")
    if args.limit_steps:
        keep = args.limit_steps * args.batch_size * world_size
        images, labels = images[:keep], labels[:keep]

    # bs per rank (reference :60-61); sampler shards, loader never reshuffles
    # across epochs (reference quirk: no sampler.set_epoch, SURVEY §2.1 C14)
    loader = ShardedBatchLoader(
        images, labels, args.batch_size, world_size, shuffle=True, seed=0
    )

    state = TrainState.create(model, rng, jnp.zeros([1, *image_shape, 1], dtype), tx)
    if args.ckpt_dir and args.resume:
        from tpu_sandbox.train import checkpoint as ckpt

        if ckpt.latest_step(args.ckpt_dir) is not None:
            state = ckpt.restore(args.ckpt_dir, state)
            print(f"resumed from step {int(state.step)}")
    dp = DataParallel(model, tx, mesh, image_size=tuple(image_shape))
    dstate = dp.shard_state(state)

    def step(s, images_np, labels_np):
        return dp.train_step(s, *dp.shard_batch(images_np, labels_np))

    trainer = Trainer(step, log_every=args.log_every, log_rank=0)
    dstate = trainer.fit(dstate, loader, args.epochs, set_epoch=False)
    if args.ckpt_dir:
        from tpu_sandbox.train import checkpoint as ckpt

        # checkpoint the single-device view (rank 0's BN stats), the same
        # layout mnist_onegpu saves — the two scripts' checkpoints interop
        print(f"saved checkpoint at step "
              f"{ckpt.save(args.ckpt_dir, dp.unshard_state(dstate))}")
    bootstrap.cleanup()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", "--nodes", type=int, default=1, metavar="N",
                        help="number of hosts (parity flag; >1 uses jax.distributed)")
    parser.add_argument("-g", "--gpus", type=int, default=1,
                        help="number of devices (ranks) per node")
    parser.add_argument("-nr", "--nr", type=int, default=0,
                        help="ranking of this node (parity flag)")
    parser.add_argument("--epochs", type=int, default=2, metavar="N",
                        help="number of epochs")
    parser.add_argument("--batch-size", type=int, default=5,
                        help="per-rank batch size (reference :60-61)")
    parser.add_argument("--image-size", type=int, default=IMAGE_SHAPE[0])
    parser.add_argument("--data-dir", type=str, default=None)
    parser.add_argument("--synthetic-n", type=int, default=60000)
    parser.add_argument("--limit-steps", type=int, default=None)
    parser.add_argument("--log-every", type=int, default=100)
    parser.add_argument("--dtype", choices=["bf16", "fp32"], default="bf16")
    parser.add_argument("--ckpt-dir", type=str, default=None,
                        help="orbax checkpoint dir (save at end of training)")
    parser.add_argument("--resume", action="store_true",
                        help="restore the latest checkpoint before training")
    parser.add_argument("--force-cpu", action="store_true",
                        help="use virtual CPU devices even if an accelerator is present")
    args = parser.parse_args()
    world_size = args.gpus * args.nodes  # reference :123
    train(args, world_size)


if __name__ == "__main__":
    main()
